"""Gate a fresh bench.py JSON line against the banked trajectory.

The repo banks one ``BENCH_r<NN>.json`` per round (the driver wraps
``bench.py`` stdout as ``{"n", "cmd", "rc", "tail"}``), but nothing
ever COMPARED a new measurement against that trajectory — a step-time
regression only surfaced when a human eyeballed the numbers.  This
tool is the missing regression gate:

- the **bank** is every ``BENCH_r*.json`` (newest = highest round);
  each file's ``tail`` is scanned for its last ``{"metric": ...}``
  line.  Error lines (tunnel down, ``value == 0``) fall back to the
  line's ``last_good`` snapshot — the trajectory stays usable across
  rounds whose hardware was unreachable.
- the **fresh** measurement is a bench JSON line (or raw bench.py
  stdout) from a file or stdin.
- the gate FAILS (exit 1) when fresh ``step_time_ms`` exceeds the
  newest usable banked step time by more than ``--max-regress-pct``
  (or when throughput ``value`` drops by more than the same bound,
  when both carry it).  A fresh error line fails too — a gate that
  passes on "the bench crashed" is not a gate.
- ``--predicted``: when the FRESHEST banked round is itself an error
  round (``status: "error"`` — the r01–r05 tunnel reality), delegate
  to the hermetic predicted-step-time bank (``tools/perf_gate.py``)
  instead of skipping silently; the verdict's ``evidence_source``
  names which trajectory gated the change.

Usage::

    python bench.py ... | python tools/bench_gate.py --fresh - \
        --max-regress-pct 10
    python tools/bench_gate.py --fresh bench_out.json \
        --bank 'BENCH_r*.json' --allow-missing-baseline

The CPU-smoke half lives in tests/test_bench_gate.py (tier-1): it
drives this gate over synthetic banked files, so the comparison logic
is exercised on every CI run without touching hardware.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

# a usable measurement needs a positive throughput and a step time —
# the two numbers the gate compares
METRIC_LINE_RE = re.compile(r'^\s*\{"metric"')


def extract_metric_line(text: str) -> Optional[Dict]:
    """Last ``{"metric": ...}`` JSON object in ``text`` (bench.py
    prints exactly one as its final line; banked files wrap whole
    stdout)."""
    last = None
    for line in text.splitlines():
        if METRIC_LINE_RE.match(line):
            try:
                last = json.loads(line)
            except json.JSONDecodeError:
                continue
    return last


def usable_measurement(line: Optional[Dict]) -> Optional[Dict]:
    """The comparable core of a bench line: the line itself when it
    carries a real measurement, else its ``last_good`` snapshot (the
    stale-but-honest fallback bench.py emits when hardware was
    unreachable), else None."""
    if not isinstance(line, dict):
        return None

    def _ok(d: Dict) -> bool:
        # an explicit error mark wins over whatever numbers rode
        # along (bench.py stamps status on every line since ISSUE 7);
        # both compared numbers must also be real: a step_time_ms of
        # 0 would divide the gate by zero as a baseline and trivially
        # PASS as a fresh line — "the bench crashed" must fail
        return (d.get("status") != "error"
                and (d.get("value", 0) or 0) > 0
                and (d.get("step_time_ms", 0) or 0) > 0)

    if _ok(line):
        return line
    lg = line.get("last_good")
    if isinstance(lg, dict) and _ok(lg):
        return lg
    return None


def _round_key(path: str) -> Tuple:
    """Sort key = the integer round parsed from the filename, so
    BENCH_r100 orders AFTER BENCH_r99 (lexicographic glob order would
    pin the baseline at r99 forever once rounds outgrow the zero
    padding); non-matching names fall back to plain name order."""
    m = re.search(r"_r(\d+)", os.path.basename(path))
    return (0, int(m.group(1)), path) if m else (1, 0, path)


def load_bank(pattern: str) -> List[Tuple[str, Dict]]:
    """[(path, usable measurement)] for every banked round that has
    one, in round order (numeric — BENCH_r99 < BENCH_r100)."""
    out = []
    for path in sorted(glob.glob(pattern), key=_round_key):
        try:
            with open(path) as f:
                payload = json.load(f)
        except (json.JSONDecodeError, OSError):
            continue
        text = payload.get("tail", "") if isinstance(payload, dict) \
            else ""
        m = usable_measurement(extract_metric_line(text))
        if m is not None:
            out.append((path, m))
    return out


def freshest_round_is_error(pattern: str) -> Optional[str]:
    """Path of the newest banked round when its OWN metric line is an
    error line (usable only via last_good, or not at all); None when
    the newest round carries a real measurement or no round exists.

    This is the --predicted trigger: five straight error rounds mean
    the measured trajectory is frozen, and gating fresh CPU rounds
    against a stale last_good carry proves nothing about THIS change.
    """
    paths = sorted(glob.glob(pattern), key=_round_key)
    if not paths:
        return None
    newest = paths[-1]
    try:
        with open(newest) as f:
            payload = json.load(f)
    except (json.JSONDecodeError, OSError):
        return newest
    text = payload.get("tail", "") if isinstance(payload, dict) else ""
    line = extract_metric_line(text)
    m = usable_measurement(line)
    if m is None or m is not line:
        return newest
    return None


def _pred_age_hours(rec: Dict) -> Optional[float]:
    """Hours since the prediction record's ``banked_at`` stamp; None
    when the stamp is missing or unparseable."""
    import calendar
    import time

    try:
        t = calendar.timegm(time.strptime(rec.get("banked_at", ""),
                                          "%Y-%m-%dT%H:%M:%SZ"))
    except (TypeError, ValueError):
        return None
    return (time.time() - t) / 3600.0


def gate_predicted(fresh_glob: str, bank_dir: str,
                   max_regress_pct: float,
                   max_age_hours: float = 24.0) -> Tuple[bool, Dict]:
    """Predicted-step-time gating: fresh prediction artifacts (a
    tools/perf_gate.py run's --fresh-dir output) vs the banked
    ``perf_pred_*.json`` baselines.  Used when the measured trajectory
    has no fresh evidence to offer (error round) — the verdict names
    its evidence source so a PASS can never masquerade as a hardware
    measurement."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        from tools.perf_gate import gate_one
    except ImportError:  # script mode: tools/ is sys.path[0]
        from perf_gate import gate_one

    verdict: Dict = {"evidence_source": "predicted",
                     "max_regress_pct": max_regress_pct,
                     "results": []}
    fresh_paths = sorted(glob.glob(fresh_glob))
    if not fresh_paths:
        verdict["error"] = (
            f"--predicted: no fresh prediction artifacts match "
            f"{fresh_glob!r} — run `python tools/perf_gate.py "
            f"--fresh-dir <dir>` first (the gate must not silently "
            "skip)")
        return False, verdict
    ok = True
    for path in fresh_paths:
        try:
            with open(path) as f:
                fresh = json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            verdict["results"].append({"fresh": path,
                                       "gate": "FAIL",
                                       "error": repr(e)})
            ok = False
            continue
        fresh.setdefault("key", os.path.splitext(
            os.path.basename(path))[0].replace("perf_pred_", ""))
        # leftovers from an earlier round must not gate THIS change:
        # a stale fresh artifact passing silently is a green verdict
        # for a prediction that was never computed
        age = _pred_age_hours(fresh)
        if age is None or age > max_age_hours:
            verdict["results"].append({
                "key": fresh["key"], "gate": "FAIL",
                "error": (
                    f"fresh prediction {path} is "
                    f"{'unstamped' if age is None else f'{age:.1f}h old'}"
                    f" (limit {max_age_hours}h) — re-run `python "
                    "tools/perf_gate.py --fresh-dir <dir>` for this "
                    "change")})
            ok = False
            continue
        # ONE gating path + row schema with tools/perf_gate.py
        row = gate_one(fresh, bank_dir, max_regress_pct,
                       allow_missing_baseline=False)
        verdict["results"].append(row)
        ok = ok and row["gate"] != "FAIL"
    return ok, verdict


def gate(fresh: Optional[Dict], bank: List[Tuple[str, Dict]],
         max_regress_pct: float,
         allow_missing_baseline: bool = False) -> Tuple[bool, Dict]:
    """(ok, verdict).  The baseline is the NEWEST usable banked round
    — the gate answers "did this change regress the trajectory", not
    "is this the best number ever banked" (the best-ever number is
    reported for context)."""
    verdict: Dict = {"max_regress_pct": max_regress_pct}
    fresh_m = usable_measurement(fresh)
    if fresh_m is None or fresh_m is not fresh:
        # an error line (or one only usable via last_good) is not a
        # fresh measurement of THIS change
        verdict["error"] = ("fresh bench line carries no usable "
                            "measurement (value<=0, missing "
                            "step_time_ms, or error payload)")
        verdict["fresh"] = fresh
        return False, verdict
    verdict["fresh"] = {k: fresh_m.get(k)
                        for k in ("value", "step_time_ms", "unit")}
    if not bank:
        verdict["baseline"] = None
        verdict["note"] = "no usable banked baseline"
        return allow_missing_baseline, verdict
    base_path, base = bank[-1]
    best = min(bank, key=lambda pm: pm[1]["step_time_ms"])
    verdict["baseline"] = {"path": base_path,
                           "value": base.get("value"),
                           "step_time_ms": base["step_time_ms"]}
    verdict["best_banked"] = {"path": best[0],
                              "step_time_ms": best[1]["step_time_ms"]}
    limit = float(base["step_time_ms"]) * (1 + max_regress_pct / 100.0)
    step_regress_pct = (float(fresh_m["step_time_ms"])
                        / float(base["step_time_ms"]) - 1) * 100.0
    verdict["step_time_regress_pct"] = round(step_regress_pct, 2)
    ok = float(fresh_m["step_time_ms"]) <= limit
    if not ok:
        verdict["error"] = (
            f"step_time_ms regressed {step_regress_pct:.1f}% vs "
            f"{base_path} ({fresh_m['step_time_ms']} > "
            f"{base['step_time_ms']} +{max_regress_pct}%)")
        return False, verdict
    # throughput cross-check when both sides carry it (value is
    # images/sec/chip — a DROP is the regression direction)
    if (base.get("value") or 0) > 0 and (fresh_m.get("value") or 0) > 0:
        tp_drop_pct = (1 - float(fresh_m["value"])
                       / float(base["value"])) * 100.0
        verdict["throughput_drop_pct"] = round(tp_drop_pct, 2)
        if tp_drop_pct > max_regress_pct:
            verdict["error"] = (
                f"throughput dropped {tp_drop_pct:.1f}% vs "
                f"{base_path} ({fresh_m['value']} < {base['value']} "
                f"-{max_regress_pct}%)")
            return False, verdict
    return True, verdict


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--fresh", required=True,
                   help="fresh bench JSON line / bench.py stdout "
                        "(file path, or '-' for stdin)")
    p.add_argument("--bank", default=None,
                   help="glob of banked rounds (default: "
                        "BENCH_r*.json next to this repo's root)")
    p.add_argument("--max-regress-pct", type=float, default=10.0,
                   help="max tolerated step-time increase (and "
                        "throughput drop) in percent [%(default)s]")
    p.add_argument("--allow-missing-baseline", action="store_true",
                   help="exit 0 when no banked round carries a "
                        "usable measurement (first round on new "
                        "hardware)")
    p.add_argument("--predicted", action="store_true",
                   help="when the FRESHEST banked round is an error "
                        "round (the r01-r05 reality), gate on the "
                        "predicted-step-time bank instead of a stale "
                        "last_good carry — fresh predictions from "
                        "--pred-fresh vs artifacts/perf_pred_*.json")
    p.add_argument("--pred-fresh", default=None,
                   help="glob of fresh prediction artifacts (a "
                        "tools/perf_gate.py --fresh-dir run) "
                        "[<repo>/artifacts/perf_fresh/perf_pred_*"
                        ".json]")
    p.add_argument("--pred-bank", default=None,
                   help="prediction-baseline dir "
                        "[<repo>/artifacts]")
    p.add_argument("--pred-max-age-hours", type=float, default=24.0,
                   help="fresh prediction artifacts older than this "
                        "FAIL as stale (leftovers from an earlier "
                        "round must not gate this change) "
                        "[%(default)s]")
    args = p.parse_args(argv)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    if args.fresh == "-":
        text = sys.stdin.read()
    else:
        with open(args.fresh) as f:
            text = f.read()
    fresh = extract_metric_line(text)

    pattern = args.bank
    if pattern is None:
        pattern = os.path.join(repo, "BENCH_r*.json")

    # --predicted: with the freshest banked round itself an error
    # round AND no fresh measurement either, the measured trajectory
    # is frozen and a fresh error line proves nothing new — delegate
    # to the hermetic prediction bank, and SAY which evidence gated
    # the change.  A fresh HEALTHY line always gates measured: a
    # hardware window's real measurement is the strongest evidence of
    # the round and can show host-side regressions the roofline model
    # cannot see.
    error_round = freshest_round_is_error(pattern)
    if (args.predicted and error_round is not None
            and (fresh is None
                 or usable_measurement(fresh) is not fresh)):
        print(f"bench_gate: freshest banked round {error_round} is "
              "an error round and the fresh line carries no "
              "measurement — gating on PREDICTED step time "
              "(tools/perf_gate.py bank), not measured hardware "
              "evidence", file=sys.stderr)
        ok, verdict = gate_predicted(
            args.pred_fresh or os.path.join(
                repo, "artifacts", "perf_fresh", "perf_pred_*.json"),
            args.pred_bank or os.path.join(repo, "artifacts"),
            args.max_regress_pct,
            max_age_hours=args.pred_max_age_hours)
        verdict["measured_error_round"] = os.path.basename(error_round)
    else:
        if args.predicted:
            why = ("the fresh line carries a real measurement"
                   if error_round is not None
                   else "the freshest banked round carries a real "
                        "measurement")
            print(f"bench_gate: {why} — gating on MEASURED evidence "
                  "(--predicted only takes over when both are error "
                  "rounds)", file=sys.stderr)
        bank = load_bank(pattern)
        ok, verdict = gate(fresh, bank, args.max_regress_pct,
                           allow_missing_baseline=args
                           .allow_missing_baseline)
        verdict["evidence_source"] = "measured"
    verdict["gate"] = "PASS" if ok else "FAIL"
    print(json.dumps(verdict, indent=1))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
