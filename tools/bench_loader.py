"""Standalone input-pipeline throughput bench (VERDICT r1 item 3).

The reference outsources this concern to TensorPack's multiprocess
DataFlow (external, container/Dockerfile:16-19); here the host must
sustain decode+resize+rasterize faster than the TPU consumes batches —
at batch 4/chip × 4 chips/host of 1344² images, roughly
``1.5 × chip_imgs_per_sec × 4`` images/sec per host.

Prints ONE JSON line:
    {"metric": "loader_throughput", "value": N, "unit":
     "images/sec/host", ...}

Run: ``python tools/bench_loader.py [--batches 20] [--workers 8]``
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

# runnable as `python tools/bench_loader.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser(description="eksml_tpu loader bench")
    p.add_argument("--image-size", type=int, default=1344,
                   help="PREPROC.MAX_SIZE operating point")
    p.add_argument("--batch-size", type=int, default=16,
                   help="per-host batch (4 chips × batch 4)")
    p.add_argument("--batches", type=int, default=20)
    p.add_argument("--workers", type=int, default=8)
    p.add_argument("--source-hw", type=int, nargs=2, default=(480, 640),
                   help="raw image size before resize (COCO median-ish)")
    p.add_argument("--no-masks", action="store_true")
    args = p.parse_args(argv)

    import numpy as np

    from eksml_tpu.config import config as cfg
    from eksml_tpu.data import DetectionLoader, SyntheticDataset

    cfg.freeze(False)
    cfg.PREPROC.MAX_SIZE = args.image_size
    cfg.PREPROC.TRAIN_SHORT_EDGE_SIZE = (args.image_size - 344,
                                         args.image_size - 320)
    cfg.freeze()

    h, w = args.source_hw
    ds = SyntheticDataset(num_images=64, height=h, width=w,
                          num_classes=cfg.DATA.NUM_CLASSES)
    loader = DetectionLoader(ds.records(), cfg, args.batch_size,
                             with_masks=not args.no_masks,
                             num_workers=args.workers)

    it = loader.batches(args.batches + 2)
    # warm: first batches pay thread-pool spin-up
    next(it)
    next(it)
    t0 = time.time()
    n = 0
    for batch in it:
        n += batch["images"].shape[0]
        assert batch["images"].shape[1] == args.image_size
    dt = time.time() - t0
    per_sec = n / dt
    print(f"loader: {n} images in {dt:.1f}s "
          f"({args.workers} workers, masks={not args.no_masks})",
          file=sys.stderr)
    cores = os.cpu_count() or 1
    print(json.dumps({
        "metric": "loader_throughput",
        "value": round(per_sec, 2),
        "unit": "images/sec/host",
        "images_per_sec_per_core": round(per_sec / cores, 2),
        "cpu_cores": cores,
        "image_size": args.image_size,
        "batch_size": args.batch_size,
        "workers": args.workers,
        "with_masks": not args.no_masks,
    }))
    return per_sec


if __name__ == "__main__":
    main()
