"""Shared helper for BENCH_LOCAL.json handling (code review r5).

The banked_at timestamp format, the staleness TTL, and the atomic
stamped write previously lived as copy-pasted python -c snippets in
THREE shell scripts (bench_retry_loop.sh, bench_supervisor.sh,
tpu_harvest.sh) — all run under 2>/dev/null where any drift between
copies silently misclassifies fresh hardware evidence as stale.  One
implementation, three callers:

    python tools/bench_local_util.py check [--path P] [--max-age S]
        exit 0 = fresh (stamped within max-age), 1 = stale/unstamped/
        unparseable/missing.
    python tools/bench_local_util.py stamp --out P ( --from-file F | JSON )
        add banked_at (UTC, second resolution) and write atomically
        (tmp+mv) so pollers never see a partial file.

Why a TTL at all: a leftover BENCH_LOCAL.json from a PRIOR round makes
the supervisor exit instantly and the harvest chain off a stale number
(ADVICE r4).  Age is an imperfect discriminator (rounds can be
back-to-back), so session starts should still remove leftovers
explicitly; this guard is defense-in-depth, and callers RENAME rather
than delete so real hardware evidence is never destroyed.
"""

from __future__ import annotations

import argparse
import calendar
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# single source of truth for the stamp format — bench.py's _bank writes
# rung/last_good files with the same TS_FMT/utcnow, so the two writer
# families cannot drift apart
from bench import TS_FMT as FMT  # noqa: E402
from bench import utcnow  # noqa: E402

DEFAULT_MAX_AGE = 7200.0


def is_fresh(path: str, max_age: float = DEFAULT_MAX_AGE) -> bool:
    """True when ``path`` parses and carries a banked_at within
    ``max_age`` seconds.  Anything else — missing file, bad JSON, no
    stamp, unparseable stamp — is stale."""
    try:
        with open(path) as f:
            d = json.load(f)
        ts = calendar.timegm(time.strptime(d["banked_at"], FMT))
    except (OSError, ValueError, KeyError, TypeError):
        return False
    return time.time() - ts <= max_age


def stamp(payload: dict, out: str) -> None:
    """Write ``payload`` + banked_at to ``out`` atomically."""
    rec = dict(payload)
    rec["banked_at"] = utcnow()
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f)
    os.replace(tmp, out)


def rotate(path: str, max_age: float = DEFAULT_MAX_AGE) -> bool:
    """Set aside ``path`` when it is stale: rename (never delete) to
    ``<path minus .json>.stale.<ts>.json``.  Returns True when the file
    is absent-or-fresh afterwards.  One implementation for the session
    -start guards in bench_supervisor.sh AND tpu_harvest.sh (code
    review r5: the block had been copy-pasted between them)."""
    if not os.path.exists(path):
        return True
    if is_fresh(path, max_age):
        return True
    base = path[:-5] if path.endswith(".json") else path
    os.replace(path, f"{base}.stale.{utcnow().replace(':', '')}.json")
    return True


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    c = sub.add_parser("check")
    c.add_argument("--path", default="BENCH_LOCAL.json")
    c.add_argument("--max-age", type=float, default=DEFAULT_MAX_AGE)
    r = sub.add_parser("rotate")
    r.add_argument("--path", default="BENCH_LOCAL.json")
    r.add_argument("--max-age", type=float, default=DEFAULT_MAX_AGE)
    s = sub.add_parser("stamp")
    s.add_argument("--out", required=True)
    s.add_argument("--from-file", default=None)
    s.add_argument("json_line", nargs="?", default=None)
    args = p.parse_args(argv)

    if args.cmd == "check":
        return 0 if is_fresh(args.path, args.max_age) else 1
    if args.cmd == "rotate":
        return 0 if rotate(args.path, args.max_age) else 1
    if args.from_file:
        with open(args.from_file) as f:
            payload = json.load(f)
    elif args.json_line:
        payload = json.loads(args.json_line)
    else:
        p.error("stamp needs --from-file or an inline JSON argument")
    stamp(payload, args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
