#!/bin/bash
# Patiently retry bench.py until a real throughput number lands.
#
# The axon TPU tunnel serializes clients and a client killed mid-compile
# wedges the server for a long time (observed round 1 and round 2) — so
# this loop (a) waits for any already-running bench to finish instead of
# racing it, (b) gives each attempt a very generous deadline so we never
# kill a compile in progress, and (c) backs off between attempts.
# First success writes the JSON line to BENCH_LOCAL.json (stamped with
# banked_at so round-end banking can apply its --since freshness filter
# — ADVICE r4) and exits; the persistent compile cache makes every later
# bench run (incl. the driver's round-end one) fast.
#
# Round 5 (VERDICT r4 next #7): bench.py now runs a sub-second TCP
# pre-flight of the tunnel port before paying the init deadline.  A
# pre-flight rejection cycles this loop in ~30s WITHOUT consuming the
# ATTEMPTS budget, so a real attempt starts within seconds of the
# tunnel coming up; every 20th consecutive rejection runs a bounded
# full-init canary (EKSML_SKIP_PREFLIGHT=1) so a relay that moved
# ports cannot permanently blind the bench.
set -u
cd "$(dirname "$0")/.."
ATTEMPTS=${ATTEMPTS:-12}
# NO timeout(1) around bench.py: SIGTERM-ing a client mid-compile is
# exactly the wedge this script exists to avoid (advisor r2).  Init
# hangs are bounded inside bench.py (--init-timeout moves on without
# killing anything); a post-init hang blocks this attempt rather than
# wedging the tunnel for everyone.
if [ -n "${PER_RUN_TIMEOUT:-}" ]; then
    echo "[loop] PER_RUN_TIMEOUT is ignored (hard kills wedge the" \
         "tunnel); attempts run unbounded with a log-only watchdog" \
         >> bench_loop.log
fi
i=0
preflight_rejects=0
while [ "$i" -lt "$ATTEMPTS" ]; do
    while pgrep -f "python bench.py" >/dev/null 2>&1; do sleep 60; done
    canary=""
    if [ "$preflight_rejects" -gt 0 ] \
        && [ $((preflight_rejects % 20)) -eq 0 ]; then
        # bounded full-init canary past the probe (false-negative
        # insurance): 1 retry x 120s, ~2 min per ~10 min of rejections
        canary=1
        echo "[loop] canary full-init (preflight_rejects=$preflight_rejects)" \
             "$(date -u +%H:%M:%S)" >> bench_loop.log
    else
        echo "[loop] attempt $((i + 1))/$ATTEMPTS $(date -u +%H:%M:%S)" \
             >> bench_loop.log
    fi
    # run in background + log-only watchdog: a post-init hang (e.g.
    # compile over a wedged tunnel) leaves a liveness trail in
    # bench_loop.log instead of silently blocking with no output
    if [ -n "$canary" ]; then
        EKSML_SKIP_PREFLIGHT=1 python bench.py --steps 20 \
            --init-retries 1 --init-timeout 120 \
            > .bench_out.tmp 2>>bench_loop.log &
    else
        python bench.py --steps 20 --init-retries 3 --init-timeout 300 \
            > .bench_out.tmp 2>>bench_loop.log &
    fi
    bpid=$!
    elapsed=0
    while kill -0 "$bpid" 2>/dev/null; do
        sleep 15
        elapsed=$((elapsed + 15))
        if [ "$elapsed" -ge 600 ] && [ $((elapsed % 600)) -eq 0 ]; then
            echo "[loop] attempt still running after ${elapsed}s" \
                 "(not killing: tunnel discipline)" >> bench_loop.log
        fi
    done
    wait "$bpid" 2>/dev/null
    out=$(tail -1 .bench_out.tmp 2>/dev/null)
    # rate-limit pre-flight rejects in the attempts ledger (every 10th,
    # matching bench_loop.log) — a multi-day dead window must not grow
    # the file by a full diag line every ~45s (code review r5); real
    # attempts and the first reject of each burst always land
    if ! grep -q "pre-flight" <<< "$out" \
        || [ $((preflight_rejects % 10)) -eq 0 ]; then
        echo "$out" >> bench_attempts.jsonl
    fi
    if python -c '
import json, sys
try:
    d = json.loads(sys.argv[1])
except Exception:
    sys.exit(1)
# hardware evidence only: a CPU-fallback backend must not declare the
# headline landed (and must not unleash the harvest chain on CPU).
# A micro-rung-only ladder (forward_only) does not end the hunt either
# — its rung file is banked, but this loop exists to land a TRAIN-step
# number (code review r5).
ok = d.get("value", 0) > 0 and not d.get("forward_only") and \
    d.get("device_kind", "").lower() not in ("", "cpu", "host")
sys.exit(0 if ok else 1)' "$out"
    then
        # stamp banked_at so tools/bank_round.py --since can tell this
        # session's number from a stale cross-round leftover; the util
        # writes tmp+mv so pollers never see a partial file
        if python tools/bench_local_util.py stamp \
            --out BENCH_LOCAL.json "$out"; then
            echo "[loop] success $(date -u +%H:%M:%S)" >> bench_loop.log
            exit 0
        fi
        # stamp failed (ENOSPC, env breakage): do NOT claim success —
        # the supervisor polls for BENCH_LOCAL.json and would wait
        # forever on a silent miss.  The fallback must still carry a
        # banked_at (shell-injected, same TS_FMT as bench.py) or the
        # rotate guards would classify this genuine hardware evidence
        # as stale and set it aside (code review r5).
        echo "[loop] STAMP FAILED; raw fallback write" \
             "$(date -u +%H:%M:%S)" >> bench_loop.log
        ts=$(date -u +%Y-%m-%dT%H:%M:%SZ)
        printf '%s' "$out" \
            | sed "s/}\$/, \"banked_at\": \"$ts\"}/" > BENCH_LOCAL.json
        exit 0
    fi
    if grep -q "pre-flight" <<< "$out"; then
        preflight_rejects=$((preflight_rejects + 1))
        if [ $((preflight_rejects % 10)) -eq 1 ]; then
            echo "[loop] tunnel port closed (pre-flight x$preflight_rejects)" \
                 "$(date -u +%H:%M:%S)" >> bench_loop.log
        fi
        sleep 30
        continue  # fast-cycle; does NOT consume the ATTEMPTS budget
    fi
    if [ -n "$canary" ]; then
        # a FAILED canary re-enters the fast cycle without i++: a
        # multi-hour dead window must not exhaust ATTEMPTS through its
        # own false-negative insurance (code review r5)
        preflight_rejects=1
        sleep 30
        continue
    fi
    preflight_rejects=0
    i=$((i + 1))
    sleep 300
done
echo "[loop] exhausted $ATTEMPTS attempts" >> bench_loop.log
exit 1
