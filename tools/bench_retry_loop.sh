#!/bin/bash
# Patiently retry bench.py until a real throughput number lands.
#
# The axon TPU tunnel serializes clients and a client killed mid-compile
# wedges the server for a long time (observed round 1 and round 2) — so
# this loop (a) waits for any already-running bench to finish instead of
# racing it, (b) gives each attempt a very generous deadline so we never
# kill a compile in progress, and (c) backs off between attempts.
# First success writes the JSON line to BENCH_LOCAL.json and exits; the
# persistent compile cache makes every later bench run (incl. the
# driver's round-end one) fast.
set -u
cd "$(dirname "$0")/.."
ATTEMPTS=${ATTEMPTS:-12}
# NO timeout(1) around bench.py: SIGTERM-ing a client mid-compile is
# exactly the wedge this script exists to avoid (advisor r2).  Init
# hangs are bounded inside bench.py (--init-timeout moves on without
# killing anything); a post-init hang blocks this attempt rather than
# wedging the tunnel for everyone.
if [ -n "${PER_RUN_TIMEOUT:-}" ]; then
    echo "[loop] PER_RUN_TIMEOUT is ignored (hard kills wedge the" \
         "tunnel); attempts run unbounded with a log-only watchdog" \
         >> bench_loop.log
fi
for i in $(seq 1 "$ATTEMPTS"); do
    while pgrep -f "python bench.py" >/dev/null 2>&1; do sleep 60; done
    echo "[loop] attempt $i/$ATTEMPTS $(date -u +%H:%M:%S)" >> bench_loop.log
    # run in background + log-only watchdog: a post-init hang (e.g.
    # compile over a wedged tunnel) leaves a liveness trail in
    # bench_loop.log instead of silently blocking with no output
    python bench.py --steps 20 --init-retries 3 --init-timeout 300 \
        > .bench_out.tmp 2>>bench_loop.log &
    bpid=$!
    elapsed=0
    while kill -0 "$bpid" 2>/dev/null; do
        sleep 60
        elapsed=$((elapsed + 60))
        if [ $((elapsed % 600)) -eq 0 ]; then
            echo "[loop] attempt $i still running after ${elapsed}s" \
                 "(not killing: tunnel discipline)" >> bench_loop.log
        fi
    done
    wait "$bpid" 2>/dev/null
    out=$(tail -1 .bench_out.tmp 2>/dev/null)
    echo "$out" >> bench_attempts.jsonl
    if python -c '
import json, sys
try:
    d = json.loads(sys.argv[1])
except Exception:
    sys.exit(1)
# hardware evidence only: a CPU-fallback backend must not declare the
# headline landed (and must not unleash the harvest chain on CPU)
ok = d.get("value", 0) > 0 and \
    d.get("device_kind", "").lower() not in ("", "cpu", "host")
sys.exit(0 if ok else 1)' "$out"
    then
        echo "$out" > BENCH_LOCAL.json
        echo "[loop] success on attempt $i" >> bench_loop.log
        exit 0
    fi
    sleep 300
done
echo "[loop] exhausted $ATTEMPTS attempts" >> bench_loop.log
exit 1
