#!/bin/bash
# Keep the patient bench retry loop alive for the whole session.
#
# Failure mode this closes (round 3): the TPU tunnel's local relay
# died mid-session, every attempt failed fast, bench_retry_loop.sh
# exhausted its ATTEMPTS budget within ~2h — and when the tunnel came
# back hours later nothing was left retrying.  The supervisor relaunches
# the loop whenever it is not running and no headline has been banked,
# and logs a cheap TCP liveness probe of the tunnel's remote-compile
# port so the session log shows exactly when the tunnel was up.
#
# Tunnel discipline is inherited from the loop itself: the supervisor
# never kills anything.
set -u
cd "$(dirname "$0")/.."
LOG=bench_supervisor.log
# EKSML_TUNNEL_PORT is bench.py's preflight knob for the same port —
# one operator setting moves both probes, with the SAME precedence as
# bench.py (EKSML_TUNNEL_PORT wins, then PROBE_PORT, then default)
PROBE_PORT=${EKSML_TUNNEL_PORT:-${PROBE_PORT:-8103}}

# A leftover BENCH_LOCAL.json from a PRIOR round would make this
# supervisor exit immediately and the harvest chain off a stale number
# (ADVICE r4 / code review r5) — at startup, set aside any copy that
# was never stamped or whose banked_at is >2h old.  Age-based (a
# restart within 2h of the session's own success keeps it; an older
# one is re-measured from the warm compile cache), and RENAMED, never
# deleted — evidence is preserved either way.
if [ -e BENCH_LOCAL.json ]; then
    python tools/bench_local_util.py rotate 2>/dev/null || true
    [ -e BENCH_LOCAL.json ] \
        || echo "[supervisor] $(date -u +%H:%M:%S) set aside stale" \
                "BENCH_LOCAL.json" >> "$LOG"
fi

probe() {  # 0 = something is listening on the tunnel port
    (exec 3<>"/dev/tcp/127.0.0.1/$PROBE_PORT") 2>/dev/null \
        && { exec 3>&-; return 0; } || return 1
}

last_state=unknown
while true; do
    if [ -s BENCH_LOCAL.json ]; then
        echo "[supervisor] $(date -u +%H:%M:%S) headline banked; exit" \
            >> "$LOG"
        exit 0
    fi
    if probe; then state=up; else state=down; fi
    if [ "$state" != "$last_state" ]; then
        echo "[supervisor] $(date -u +%H:%M:%S) tunnel $state" >> "$LOG"
        last_state=$state
    fi
    if ! pgrep -f "bench_retry_loop.sh" >/dev/null 2>&1; then
        echo "[supervisor] $(date -u +%H:%M:%S) relaunching retry loop" \
            >> "$LOG"
        ATTEMPTS=${ATTEMPTS:-100} nohup bash tools/bench_retry_loop.sh \
            >/dev/null 2>&1 &
    fi
    sleep 120
done
