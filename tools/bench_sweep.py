"""Operating-point sweep: run bench.py across configurations and bank
the results as one artifact.

Sweeps the perf-relevant axes the optimized chart exposes — ROIAlign
backend (Pallas vs XLA), precision, remat — each as a separate
``bench.py`` subprocess so a wedged/crashed configuration can't take
the others down (the TPU tunnel serves one client at a time; runs are
strictly sequential).

Usage::

    python tools/bench_sweep.py --out artifacts/bench_sweep_r2.json \
        [--steps 20] [--quick] [--platform cpu]

``--quick``: tiny shapes for a plumbing smoke on CPU.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

CONFIGS = [
    # (name, extra argv, config KEY=VALUEs) — first entry is the
    # headline operating point (full auto: pallas fwd+bwd where probed)
    ("pallas_bf16", ["--roi-backend", "auto"], []),
    ("xla_bf16", ["--roi-backend", "xla", "--roi-bwd", "xla"], []),
    # backward-kernel isolation pair: pallas fwd fixed, bwd varies
    ("pallas_bf16_bwdxla", ["--roi-backend", "pallas",
                            "--roi-bwd", "xla"], []),
    ("pallas_bf16_bwdpallas", ["--roi-backend", "pallas",
                               "--roi-bwd", "pallas"], []),
    ("pallas_bf16_remat", ["--roi-backend", "auto", "--remat"], []),
    ("pallas_f32", ["--roi-backend", "auto",
                    "--precision", "float32"], []),
    # the optimized chart's landscape bucket (PREPROC.BUCKETS): the
    # canvas ~all landscape COCO images train at — quantifies the
    # bucketed-padding win over the 1344 square above
    ("pallas_bf16_bucket", ["--roi-backend", "auto",
                            "--pad-hw", "832", "1344"], []),
    # legacy f32 host-normalized ingest (PREPROC.DEVICE_NORMALIZE off)
    ("pallas_bf16_f32ingest", ["--roi-backend", "auto"],
     ["PREPROC.DEVICE_NORMALIZE=False"]),
]

QUICK_SHAPES = ["--image-size", "128", "--batch-size", "1",
                "--warmup", "1"]
# canonical shrunk-model profile (single source: eksml_tpu.config).
# Its PREPROC keys overwrite bench.py's CLI-derived cfg values
# (update_args runs last), but the benched batch shape still follows
# --image-size/--pad-hw: make_synthetic_batch re-derives PREPROC from
# the requested shape internally.
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from eksml_tpu.config import SMOKE_OVERRIDES  # noqa: E402
from eksml_tpu.fsio import atomic_write_json  # noqa: E402

QUICK_CONFIG = list(SMOKE_OVERRIDES)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--out", default="artifacts/bench_sweep.json")
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--timeout", type=float, default=None,
                   help="per-configuration wall clock budget (s). "
                        "Default: NO timeout on accelerator runs "
                        "(killing a TPU client mid-compile wedges the "
                        "tunnel for everyone) but 1500s for --quick "
                        "CPU smokes, where a hang is just a hang")
    p.add_argument("--quick", action="store_true")
    p.add_argument("--platform", default=None)
    args = p.parse_args(argv)

    if args.timeout is None:
        args.timeout = 1500 if args.quick else 0

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    results = []
    for name, extra, config in CONFIGS:
        if args.quick and "pallas" in extra:
            # forced-pallas configs cannot run on the CPU smoke
            # (Mosaic kernels need hardware or interpret mode); skip
            # rather than bank expected-by-construction failures
            print(f"{name}: skipped (forced pallas, --quick)",
                  file=sys.stderr)
            continue
        if args.quick and "--pad-hw" in extra:
            # scale the rectangular canvas down with the quick shapes
            # so the bucket path still runs distinctly (dims % 64 == 0)
            i = extra.index("--pad-hw")
            extra = extra[:i + 1] + ["128", "192"] + extra[i + 3:]
        # --single: each sweep row measures exactly its named operating
        # point — bench.py's default is now the escalation ladder
        cmd = [sys.executable, os.path.join(repo, "bench.py"),
               "--single", "--steps", str(args.steps)] + extra
        if args.platform:
            cmd += ["--platform", args.platform]
        if args.quick:
            cmd += QUICK_SHAPES
            config = config + QUICK_CONFIG
        if config:
            cmd += ["--config"] + config
        t0 = time.time()
        entry = {"config": name}
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=args.timeout or None, cwd=repo)
            line = out.stdout.strip().splitlines()[-1] if out.stdout \
                else ""
            entry.update(json.loads(line))
        except subprocess.TimeoutExpired:
            entry["error"] = f"timeout after {args.timeout:.0f}s"
        except (json.JSONDecodeError, IndexError):
            entry["error"] = "no JSON line"
            entry["stderr_tail"] = out.stderr.splitlines()[-3:]
        entry["wall_s"] = round(time.time() - t0, 1)
        results.append(entry)
        print(f"{name}: "
              f"{entry.get('value', entry.get('error'))}", file=sys.stderr)

    payload = {"sweep": results}
    print(json.dumps(payload))
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    atomic_write_json(args.out, payload)


if __name__ == "__main__":
    main()
