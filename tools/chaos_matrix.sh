#!/bin/bash
# Run the full chaos ladder locally with a per-rung pass/fail summary.
#
# Every rung drives one failure mode of the resilience layer
# (eksml_tpu/resilience/: graceful preemption / checkpoint integrity /
# divergence sentinel / hang watchdog) or of the fault-tolerant data
# ingest (eksml_tpu/data/robust.py: quarantine + substitution /
# bounded I/O retry / decode-pool self-healing / starvation watchdog).
# The proc-sigterm-graceful and proc-nan-rollback rungs additionally
# assert the telemetry layer (eksml_tpu/telemetry/): the flight
# recorder captured the incident chain in order, /metrics scraped as
# valid OpenMetrics mid-run, and run_report.py renders the post-mortem.
# proc-debugz-profile drives the span-tracing layer (ISSUE 5): a
# mid-run /debugz/profile capture lands Chrome-trace span artifacts,
# trace_summary --merge names dominant spans, losses stay
# bit-identical with tracing on.
# unit-goodput covers the goodput-ledger math (ISSUE 13: bucket
# classification, restart-gap recovery, reshard boundaries, coarse
# degradation) and the torn-trace-tolerant cross-host merge;
# proc-goodput-preempt is the runtime proof: SIGTERM + relaunch, the
# merged ledger shows nonzero downtime/checkpoint_restore buckets and
# a wall-clock-consistent ratio, eksml_goodput_ratio scrapes live.
# unit-lint runs eksml-lint (eksml_tpu/analysis/, ISSUE 8) over the
# real tree via tests/test_lint.py — the framework-invariant static
# gate (jit purity, post-override config drift, signal-handler
# safety, atomic writes, scope coverage, chart/values sync).
# proc-elastic-resume drives the elastic-topology subsystem (ISSUE
# 10): SIGTERM at 8 fake chips fsdp(8), relaunch at 4 chips fsdp(4)
# (same global batch), grow back to 8 — each crossing must reshard
# the restore (checkpoint_resharded event + saved→current diff) and
# continue the loss stream from the forced checkpoint.
# unit-lint-spmd runs the v2 cross-module SPMD rules (ISSUE 9:
# collective-order, rng-discipline, host-sync, recompile-hazard) over
# fixtures AND the real tree; proc-spmd-collective-skip is the
# runtime counterpart: a rank-conditional collective skip on a real
# 2-process mesh wedges/dies, and the SAME construct is flagged
# statically — the lint finding and the hang are one bug, proven once.
# unit-lint-concurrency runs the v3 thread-topology rules (ISSUE 12:
# lock-order, unlocked-shared-state, blocking-under-lock) over
# fixtures AND the real tree; proc-lock-inversion is their runtime
# counterpart: a two-thread A→B / B→A lock inversion provably wedges
# under a test timeout while the SAME source lints to the lock-order
# finding at the same lines — again one bug, proven once.
# unit-sharding-2d covers the tensor / 2d (fsdp x tensor) sharding
# plans (ISSUE 15): model-axis rule placement on the FPN/head output
# features, plan_mesh axis-product validation, tensor/2d-vs-
# replicated loss parity on the 8-device mesh, the fsdp(8) → 2d(4x2)
# elastic restore crossing, and the slow full-width dryrun entries
# (bit-pinned 8.8102 loss at <= 1/4 replicated state bytes).  The
# unit-sharding rung excludes these (-k 'not (tensor or 2d)') so the
# minutes-long full-width dryrun compiles run once per ladder, not
# twice.
# unit-serve covers the online serving subsystem (ISSUE 14,
# eksml_tpu/serve/): AOT bucket-cache warmup with a zero-request-path-
# compile counter, batch-of-N bit-identical to padded sequential
# singles, bucket force-fit, MAX_BATCH_DELAY_MS=0 pass-through,
# warmup-gated /healthz, graceful drain, and the load generator's
# artifact math.  proc-serve-drain is the runtime proof: a live
# `python -m eksml_tpu.serve` under tools/serve_loadtest.py traffic
# takes SIGTERM mid-load — zero dropped in-flight requests, 503 on
# new ones, clean exit 0, and the mid-run /metrics scrape parses the
# eksml_serve_* family set as strict OpenMetrics.
# unit-serve-reload covers the continuous-deployment layer (ISSUE 17,
# eksml_tpu/serve/reload.py + tools/eksml_operator.py --promote):
# swap-under-load bit-parity (responses match offline inference under
# BOTH param sets, every response naming the checkpoint that served
# it), rejected candidates (unreadable manifest, failed restore,
# structure mismatch, mid-drain) leaving the old params serving with
# a serve_reload_rejected event, the promotion_verdict decision table
# (error-rate gate first — a dead canary rolls back, never holds
# forever), shadow-score drift math, and the preemption-forecast
# publisher.  proc-serve-reload is the runtime proof: a live server
# under open-loop load hot-reloads a checkpoint published mid-run —
# zero dropped/errored requests, zero request-path compiles, and the
# response stream flips params_step exactly at the recorded
# serve_reload boundary; a corrupted-manifest candidate is rejected
# with the old params still serving.  proc-canary-rollback drives the
# full rollout loop: incumbent + canary servers on different steps,
# a recorded request bank replayed as shadow traffic, the promotion
# controller scoring the pair — a regressed canary is rolled back to
# the incumbent's step, then (lenient gates) a healthy one promoted.
# unit-autoscale covers the elastic-autoscaling decision half (ISSUE
# 16, eksml_tpu/resilience/autoscale.py + tools/eksml_operator.py):
# plan_mesh-pinned topology ladders, the pure decide() driven through
# capacity-trace table tests (grow/shrink/hold, hysteresis streaks,
# cooldown, forecast + goodput vetoes, thrash-resistance), static
# purity of the policy module, and the operator's scrape/capacity/
# kubectl plumbing.  proc-capacity-wave is the headline runtime
# proof: the operator drives an UNATTENDED 8→4→8 fake-chip capacity
# wave for two full cycles — every transition through the forced-
# checkpoint path (SIGTERM → exit 77 → relaunch, elastic resume
# resharding), the loss stream continuous throughout, and the merged
# goodput ledger attributing the bounded between-relaunch downtime.
# unit-multislice covers the hierarchical multi-slice gradient
# exchange (ISSUE 18): the explicit 'slice' mesh axis and straddle
# refusal in plan_mesh, the staged ICI-RS → DCN-AR → ICI-AG exchange
# specs with bit-identical storage_grads values, the three-phase ring
# price (hierarchical strictly under the flat DCN ring at every
# priced size), the slices column in the perf-gate rows, and the
# topology manifest carrying the slice count through a JSON
# round-trip.  proc-slice-loss is the runtime proof: SIGKILL a
# 2-slice 8-chip run mid-epoch, elastically resume single-slice at 4
# chips (flat exchange — one slice has no DCN hop), then grow back
# to 2 slices — every crossing resharded, the loss stream continuous.
# unit-comms covers the communication observatory (ISSUE 19): the
# replica_groups parser (explicit + iota forms, source_target_pairs),
# the ici/dcn/mixed link classification from slice straddling (no
# opcode heuristic on any pricing path), the per-collective ledger +
# comms_ms rollup, the exposed-time start/done walk, and the
# run_report Communication section with its pointer degradation.
# unit-hbm covers the HBM observatory (ISSUE 20): liveness peak math
# on hand-rolled HLO (donation credit, fusion transients, last-use
# frees), per-component live-at-peak attribution, the capacity and
# peak-regression gate verdicts, the replicated-vs-2d strict peak
# ordering, and the run_report Memory section with its pointer
# degradation.
# The subprocess (proc-*) rungs launch real `python -m eksml_tpu.train`
# (or `-m eksml_tpu.serve`) processes and are marked slow (excluded
# from tier-1); the unit and data-* rungs run in seconds.  Everything runs under
# JAX_PLATFORMS=cpu with the tiny-model overrides, sharing ONE XLA
# compile via the module-scoped cache.
#
# Usage:  tools/chaos_matrix.sh [--fast]
#   --fast   unit rungs only (skip the subprocess trainer rungs)
set -u
cd "$(dirname "$0")/.."

FAST=0
[ "${1:-}" = "--fast" ] && FAST=1

# name|pytest target — order is the ladder: cheap mechanisms first,
# then the full subprocess failure modes
RUNGS=(
  "unit-watchdog|tests/test_resilience.py -k watchdog"
  "unit-sentinel|tests/test_resilience.py -k sentinel"
  "unit-ckpt-integrity|tests/test_resilience.py -k 'manifest or corrupt or truncated or digest or fatal or all_steps'"
  "unit-preemption|tests/test_resilience.py -k preemption"
  "unit-init-retry|tests/test_resilience.py tests/test_distributed.py -k 'retry or retries or exhaustion'"
  "unit-data-robust|tests/test_data_robust.py"
  "unit-telemetry|tests/test_telemetry.py tests/test_run_report.py"
  "unit-tracing|tests/test_tracing.py tests/test_bench_gate.py"
  "unit-goodput|tests/test_goodput.py tests/test_trace_summary.py"
  "unit-sharding|tests/test_sharding.py -k 'not (tensor or 2d)'"
  "unit-sharding-2d|tests/test_sharding.py -k 'tensor or 2d'"
  "unit-multislice|tests/test_sharding.py tests/test_parallel.py tests/test_perf_gate.py -k 'slice or hierarchical or multislice'"
  "unit-perfgate|tests/test_perf_gate.py"
  "unit-comms|tests/test_comms_observatory.py"
  "unit-hbm|tests/test_memory_observatory.py"
  "unit-serve|tests/test_serve.py"
  "unit-serve-reload|tests/test_serve_reload.py"
  "unit-autoscale|tests/test_autoscale.py"
  "unit-lint|tests/test_lint.py"
  "unit-lint-spmd|tests/test_lint_spmd.py"
  "unit-lint-concurrency|tests/test_lint_concurrency.py"
  "data-corrupt-jpeg|'tests/test_fault_tolerance.py::test_data_fault_rung[corrupt-jpeg]'"
  "data-missing-file|'tests/test_fault_tolerance.py::test_data_fault_rung[missing-file]'"
  "data-eio-recover|'tests/test_fault_tolerance.py::test_data_fault_rung[eio-recover]'"
  "data-broken-pool|tests/test_fault_tolerance.py::test_broken_pool_rebuilds_and_continues"
  "proc-sigkill-resume|tests/test_fault_tolerance.py::test_sigkill_then_resume"
  "proc-sigterm-graceful|tests/test_fault_tolerance.py::test_sigterm_graceful_preempt_then_resume"
  "proc-elastic-resume|tests/test_fault_tolerance.py::test_elastic_resume_grow_shrink"
  "proc-slice-loss|tests/test_fault_tolerance.py::test_slice_loss_shrink_grow"
  "proc-capacity-wave|tests/test_fault_tolerance.py::test_operator_capacity_wave"
  "proc-corrupt-latest|tests/test_fault_tolerance.py::test_corrupt_latest_checkpoint_falls_back"
  "proc-nan-rollback|tests/test_fault_tolerance.py::test_nan_loss_rolls_back_and_never_checkpoints_poison"
  "proc-debugz-profile|tests/test_fault_tolerance.py::test_debugz_profile_capture_midrun_with_tracing"
  "proc-goodput-preempt|tests/test_fault_tolerance.py::test_goodput_ledger_across_preempt_relaunch"
  "proc-spmd-collective-skip|tests/test_fault_tolerance.py::test_rank_conditional_collective_skip_hangs_and_lints"
  "proc-lock-inversion|tests/test_fault_tolerance.py::test_lock_inversion_wedges_and_lints"
  "proc-serve-drain|tests/test_fault_tolerance.py::test_serve_drain_under_load"
  "proc-serve-reload|tests/test_fault_tolerance.py::test_serve_hot_reload_under_load"
  "proc-canary-rollback|tests/test_fault_tolerance.py::test_canary_shadow_score_and_rollback"
  "proc-data-chaos|tests/test_fault_tolerance.py::test_data_chaos_train_completes_with_quarantine"
  "proc-data-breaker|tests/test_fault_tolerance.py::test_quarantine_overflow_aborts_actionably"
)

declare -a NAMES RESULTS TIMES
fails=0
for rung in "${RUNGS[@]}"; do
  name="${rung%%|*}"
  target="${rung#*|}"
  if [ "$FAST" = 1 ] && [[ "$name" == proc-* ]]; then
    NAMES+=("$name"); RESULTS+=("SKIP"); TIMES+=("-")
    continue
  fi
  echo "=== rung: $name ==="
  t0=$(date +%s)
  # eval keeps the single-quoted -k expressions intact
  if eval "JAX_PLATFORMS=cpu python -m pytest $target -q \
      -p no:cacheprovider -p no:randomly"; then
    RESULTS+=("PASS")
  else
    RESULTS+=("FAIL"); fails=$((fails + 1))
  fi
  NAMES+=("$name"); TIMES+=("$(( $(date +%s) - t0 ))s")
done

echo
echo "==================== chaos matrix ===================="
printf '%-24s %-6s %s\n' "rung" "result" "time"
for i in "${!NAMES[@]}"; do
  printf '%-24s %-6s %s\n' "${NAMES[$i]}" "${RESULTS[$i]}" "${TIMES[$i]}"
done
echo "======================================================"
if [ "$fails" -gt 0 ]; then
  echo "LADDER FAILED: $fails rung(s) red"
  exit 1
fi
echo "ladder green"
