"""Convergence-evidence run (VERDICT r1 item 7).

Trains the full Mask-RCNN pipeline for a few hundred steps on the
learnable shapes dataset (tools/make_shapes_coco.py — real COCO is
unreachable without egress), then asserts the two convergence facts the
reference's manual ladder watches in TensorBoard
(charts/maskrcnn/values.yaml:16):

  1. total_loss drops materially from its early average, and
  2. val bbox AP is meaningfully > 0 by the end.

Writes the loss curve + final APs as a JSON artifact for the repo
(artifacts/convergence_rN.json).

Usage::

    python tools/convergence_run.py --steps 300 --out \
        artifacts/convergence_r2.json [--platform cpu] [--size 320]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

# runnable as `python tools/convergence_run.py` from anywhere: the repo
# root (eksml_tpu, tools) may not be on sys.path
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check_convergence(early: float, late: float, ap50: float) -> None:
    """Material-convergence gate.  Held-out AP is the ground truth;
    the loss check admits a strong-AP exemption because Mask-RCNN's
    TOTAL loss is not monotone in convergence — better RPN proposals
    activate more fg samples, growing the fg-normalized head/mask
    terms (observed r3: loss +14% while val bbox AP50 hit 0.53)."""
    assert late < 0.7 * early or ap50 >= 0.5, \
        f"no material convergence: loss {early:.3f} -> {late:.3f}" \
        f" and bbox AP50 only {ap50:.3f}"
    assert ap50 > 0.05, f"bbox AP50 too low: {ap50}"


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=300)
    p.add_argument("--size", type=int, default=320)
    p.add_argument("--num-train", type=int, default=200)
    p.add_argument("--num-val", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=2)
    p.add_argument("--out", default=None)
    p.add_argument("--platform", default=None,
                   help="force jax platform (cpu/tpu)")
    p.add_argument("--data", default=None,
                   help="reuse an existing shapes dataset dir")
    p.add_argument("--no-check", action="store_true",
                   help="emit the artifact without convergence asserts "
                        "(pipeline smoke)")
    p.add_argument("--config", nargs="*", default=[],
                   help="KEY=VALUE overrides (e.g. shrink the model "
                        "for a CPU smoke)")
    args = p.parse_args(argv)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from eksml_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()  # a rerun must never re-pay the compile

    import numpy as np

    from tools.make_shapes_coco import make_split

    if args.data:
        base = args.data
    else:
        base = tempfile.mkdtemp(prefix="shapes_coco_")
        make_split(base, "train2017", args.num_train, args.size, 0, 1000)
        make_split(base, "val2017", args.num_val, args.size, 1, 100000)
        print(f"dataset at {base}", file=sys.stderr)

    from eksml_tpu.config import config as cfg
    from eksml_tpu.config import finalize_configs
    from eksml_tpu.data import CocoDataset, DetectionLoader
    from eksml_tpu.evalcoco import run_evaluation
    from eksml_tpu.train import Trainer

    size = args.size
    cfg.freeze(False)
    cfg.DATA.BASEDIR = base
    cfg.DATA.NUM_CLASSES = 4          # BG + box/blob/wedge
    cfg.PREPROC.MAX_SIZE = size
    cfg.PREPROC.TRAIN_SHORT_EDGE_SIZE = (size, size)
    cfg.PREPROC.TEST_SHORT_EDGE_SIZE = size
    cfg.DATA.MAX_GT_BOXES = 8
    cfg.TRAIN.BASE_LR = 0.01
    cfg.TRAIN.WARMUP_STEPS = 100
    # boundary far past the run but int32-safe after the ×8/batch
    # rescale (a 1e9 sentinel overflowed jit argument parsing)
    cfg.TRAIN.LR_SCHEDULE = (10 ** 6,)  # constant post-warmup
    flag_steps = args.steps
    flag_log_period = max(1, min(10, flag_steps // 6))
    cfg.TRAIN.STEPS_PER_EPOCH = flag_steps
    cfg.TRAIN.MAX_EPOCHS = 1
    cfg.TRAIN.CHECKPOINT_PERIOD = 1
    cfg.TRAIN.LOG_PERIOD = flag_log_period
    cfg.TRAIN.NUM_CHIPS = 1
    cfg.TPU.MESH_SHAPE = (1, 1)
    cfg.BACKBONE.WEIGHTS = ""
    logdir = os.path.join(base, "run")
    cfg.TRAIN.LOGDIR = logdir
    cfg.update_args(args.config)
    finalize_configs(is_training=True)
    # cfg is the source of truth after update_args: a --config
    # TRAIN.STEPS_PER_EPOCH override must change the run length too,
    # not just the LR bookkeeping the copy above feeds
    steps = int(cfg.TRAIN.STEPS_PER_EPOCH)
    log_overridden = any(
        o.split("=", 1)[0].strip() == "TRAIN.LOG_PERIOD"
        for o in args.config)
    if steps != flag_steps and not log_overridden:
        # the logging cadence was derived from the flag above; follow
        # the overridden run length UNLESS the operator overrode
        # LOG_PERIOD itself (then their value wins — detected by key,
        # not by value, so an explicit override that happens to equal
        # the derived cadence still wins)
        cfg.freeze(False)
        cfg.TRAIN.LOG_PERIOD = max(1, min(10, steps // 6))
        cfg.freeze()

    ds = CocoDataset(base, "train2017")
    records = ds.records()
    loader = DetectionLoader(records, cfg, args.batch_size,
                             is_training=True, seed=0,
                             with_masks=cfg.MODE_MASK)

    trainer = Trainer(cfg, logdir)
    t0 = time.time()
    state = trainer.fit(loader.batches(None), total_steps=steps)
    train_time = time.time() - t0

    # loss curve from the metric writer's JSONL
    curve = []
    with open(os.path.join(logdir, "metrics.jsonl")) as f:
        for line in f:
            d = json.loads(line)
            if "total_loss" in d:
                curve.append({"step": d["step"],
                              "total_loss": round(d["total_loss"], 4)})

    val = CocoDataset(base, "val2017").records(skip_empty=False)
    results = run_evaluation(trainer.model, state.params, cfg, val)

    n = max(1, len(curve) // 5)
    early = float(np.mean([c["total_loss"] for c in curve[:n]]))
    late = float(np.mean([c["total_loss"] for c in curve[-n:]]))
    summary = {
        "steps": steps,
        "image_size": size,
        "batch_size": args.batch_size,
        "overrides": list(args.config),
        "train_seconds": round(train_time, 1),
        "early_loss": round(early, 4),
        "late_loss": round(late, 4),
        "loss_drop_pct": round(100 * (1 - late / early), 1),
        "bbox_AP": round(results.get("bbox/AP", -1), 4),
        "bbox_AP50": round(results.get("bbox/AP50", -1), 4),
        "segm_AP": round(results.get("segm/AP", -1), 4),
        # segm AP50 banked alongside bbox AP50 so mask quality is
        # compared like-for-like (VERDICT r3 weak #2 read segm_AP
        # (0.5:0.95) against bbox_AP50 (0.5) — at matched thresholds
        # the r3 run's masks tracked boxes closely: bbox_AP 0.2163 vs
        # segm_AP 0.2131)
        "segm_AP50": round(results.get("segm/AP50", -1), 4),
        "device": jax.devices()[0].device_kind,
        "curve": curve,
    }
    out = json.dumps(summary)
    print(out)
    if args.out:
        from eksml_tpu.fsio import atomic_write_text

        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        atomic_write_text(args.out, out + "\n")

    if not args.no_check:
        check_convergence(early, late, results.get("bbox/AP50", 0))
        print("convergence OK", file=sys.stderr)


if __name__ == "__main__":
    main()
