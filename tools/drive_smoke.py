"""Consumer-style end-to-end smoke drive (CPU).

The verify recipe's standing drive script (.claude/skills/verify):
exercises config -> loader -> Trainer.fit exactly as a framework
consumer would, with the ROIAlign auto-gate forced down its
probe-thread path: the REAL hardware probe (_probe_compile) runs in
the fresh probe thread MID-TRACE; on CPU Mosaic is unavailable, so the
probe must fail GRACEFULLY inside its thread (never poisoning the
outer trace) and fall back to XLA — and training must still step with
a finite loss.  Copy + adapt for change-specific drives.
"""
import os
import shutil
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# hermetic: none of the kernel/precision env switches may leak in
for var in ("EKSML_ROI_BACKEND", "EKSML_ROI_BWD",
            "EKSML_DEFAULT_PRECISION"):
    os.environ.pop(var, None)

import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

from eksml_tpu.config import config as cfg, finalize_configs
from eksml_tpu.data import DetectionLoader, SyntheticDataset
from eksml_tpu.train import Trainer

logdir = tempfile.mkdtemp(prefix="drive_smoke_")  # fresh: a reused
# logdir would auto-resume past total_steps and skip training entirely

cfg.update_args([
    "PREPROC.MAX_SIZE=128", "PREPROC.TRAIN_SHORT_EDGE_SIZE=(128,128)",
    "PREPROC.TEST_SHORT_EDGE_SIZE=128", "DATA.MAX_GT_BOXES=8",
    "DATA.SYNTHETIC=True", "RPN.TRAIN_PRE_NMS_TOPK=128",
    "RPN.TRAIN_POST_NMS_TOPK=64", "RPN.TEST_PRE_NMS_TOPK=128",
    "RPN.TEST_POST_NMS_TOPK=64", "FRCNN.BATCH_PER_IM=32",
    "TEST.RESULTS_PER_IM=8", "TRAIN.STEPS_PER_EPOCH=2",
    "TRAIN.MAX_EPOCHS=1", "TRAIN.CHECKPOINT_PERIOD=1",
    "TRAIN.LOG_PERIOD=1", "TRAIN.WARMUP_STEPS=10",
    f"TRAIN.LOGDIR={logdir}", "TPU.MESH_SHAPE=(1,1)",
    "BACKBONE.RESNET_NUM_BLOCKS=(1,1,1,1)", "FPN.NUM_CHANNEL=32",
    "FPN.FRCNN_FC_HEAD_DIM=64", "MRCNN.HEAD_DIM=16",
])
finalize_configs(is_training=True)

ds = SyntheticDataset(num_images=4, height=128, width=128,
                      num_classes=cfg.DATA.NUM_CLASSES)
loader = DetectionLoader(ds.records(), cfg, batch_size=1,
                         with_masks=True, gt_mask_size=28)

from eksml_tpu.ops.pallas import roi_align_kernel as rk

rk._PROBE_RESULTS.clear()
rk._BWD_PROBE.clear()

# Build the Trainer BEFORE faking the backend so its collective-flag
# setup (which is also backend-gated) runs in honest CPU mode; only
# the model trace inside fit() then sees the fake "tpu" and probes.
trainer = Trainer(cfg, logdir)
orig_backend = rk.jax.default_backend
rk.jax.default_backend = lambda: "tpu"
try:
    state = trainer.fit(loader.batches(None), total_steps=2)
finally:
    rk.jax.default_backend = orig_backend

step = int(np.asarray(state.step))
assert step == 2, step
# the probe ran for the ACTUAL compute dtype and failed gracefully
key = "bfloat16" if cfg.TRAIN.PRECISION == "bfloat16" else "float32"
assert rk._PROBE_RESULTS.get(key) is False, rk._PROBE_RESULTS
# a finite loss actually came out of the stepped model
import json

with open(os.path.join(logdir, "metrics.jsonl")) as f:
    losses = [json.loads(l)["total_loss"] for l in f
              if "total_loss" in l]
assert losses and all(np.isfinite(v) for v in losses), losses
shutil.rmtree(logdir, ignore_errors=True)
print("DRIVE PASSED: probe-thread ran+fell back, trained to step",
      step, "loss", [round(v, 3) for v in losses])
