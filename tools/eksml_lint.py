"""eksml-lint CLI: framework-invariant static analysis gating CI.

Runs the thirteen rules in ``eksml_tpu/analysis/`` over the
production tree (eksml_tpu/, tools/, bench.py — tests are excluded on
purpose) and exits nonzero on any finding that is neither suppressed
inline (``# eksml-lint: disable=<rule>``) nor grandfathered in the
committed baseline: the six v1 module/project rules, the four v2
SPMD-safety rules on the cross-module call graph, and the three v3
thread-topology concurrency rules (lock-order, unlocked-shared-state,
blocking-under-lock).  tests/test_lint.py runs this over the real
repo, which makes every invariant a tier-1 gate.

Usage::

    python tools/eksml_lint.py                      # full gate
    python tools/eksml_lint.py --json               # machine output
    python tools/eksml_lint.py --rules atomic-write eksml_tpu/
    python tools/eksml_lint.py --changed            # pre-commit path:
                                                    # findings only in
                                                    # files changed vs
                                                    # HEAD (--changed
                                                    # BASE for a ref)
    python tools/eksml_lint.py --update-baseline    # grandfather debt
                                                    # (then justify
                                                    # every entry!)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from eksml_tpu.analysis import ALL_RULES, load_baseline, run_lint  # noqa: E402
from eksml_tpu.analysis.engine import format_human, write_baseline  # noqa: E402

DEFAULT_BASELINE = os.path.join(REPO, "tools", "lint_baseline.json")


def changed_paths(base: str, repo: str = REPO) -> list:
    """Repo-relative paths of files changed vs *base* (``git diff
    --name-only``) plus untracked files — the pre-commit scope."""
    out = subprocess.run(["git", "diff", "--name-only", base, "--"],
                         cwd=repo, capture_output=True, text=True)
    if out.returncode != 0:
        raise RuntimeError(
            f"git diff --name-only {base} failed: "
            f"{out.stderr.strip() or out.stdout.strip()}")
    paths = [ln.strip() for ln in out.stdout.splitlines() if ln.strip()]
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=repo, capture_output=True, text=True)
    if untracked.returncode == 0:
        paths += [ln.strip() for ln in untracked.stdout.splitlines()
                  if ln.strip()]
    return sorted(set(paths))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("targets", nargs="*", default=None,
                   help="files/dirs to lint (default: the production "
                        "tree — eksml_tpu/, tools/, bench.py)")
    p.add_argument("--rules", default=None,
                   help=f"comma list of {list(ALL_RULES)} "
                        "(default: all)")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="grandfathered-findings file [%(default)s]")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline (show total debt)")
    p.add_argument("--update-baseline", action="store_true",
                   help="write current findings to the baseline; "
                        "every entry then needs a justified 'reason'")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable output")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="BASE",
                   help="report findings only for files in `git diff "
                        "--name-only BASE` (default HEAD) plus "
                        "untracked files — the fast pre-commit path. "
                        "The cross-module graph is still built over "
                        "the full tree, so a changed caller is "
                        "checked against unchanged callees")
    args = p.parse_args(argv)

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    baseline = ([] if (args.no_baseline or args.update_baseline)
                else load_baseline(args.baseline))
    if args.changed is not None and args.update_baseline:
        # the merge in write_baseline keys "still present" off the
        # checked files; a path-filtered result would silently kill
        # grandfathered entries for unchanged files
        print("eksml-lint: --changed cannot be combined with "
              "--update-baseline (a scoped result would drop "
              "out-of-scope baseline entries)", file=sys.stderr)
        return 2
    only_paths = None
    if args.changed is not None:
        try:
            only_paths = changed_paths(args.changed)
        except RuntimeError as e:
            print(f"eksml-lint: {e}", file=sys.stderr)
            return 2
        if not only_paths:
            print(f"eksml-lint: no files changed vs {args.changed} — "
                  "nothing to lint")
            return 0
    result = run_lint(targets=args.targets or None, repo_root=REPO,
                      rules=rules, baseline=baseline,
                      only_paths=only_paths)

    if args.update_baseline:
        # scoped updates merge: out-of-scope grandfathered entries and
        # hand-written reasons survive (see write_baseline)
        write_baseline(args.baseline, result.findings,
                       active_rules=rules or list(ALL_RULES),
                       checked_paths=result.files)
        print(f"eksml-lint: baselined {len(result.findings)} "
              f"finding(s) into {args.baseline} — justify every "
              "entry's 'reason' or fix it", file=sys.stderr)
        return 0

    if args.as_json:
        print(json.dumps(result.to_dict(), indent=1))
    else:
        print(format_human(result))
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
