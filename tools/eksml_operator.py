#!/usr/bin/env python
"""Elastic autoscaling operator: actuate the pure scale policy.

The actuator half of ISSUE 16 (the decision half is
``eksml_tpu/resilience/autoscale.py``, pure and deterministic).  One
tick = read capacity from a pluggable provider → scrape the trainer's
``/metrics`` for health (goodput ratio, badput buckets, preemption
counters) → one ``decide()`` → actuate.  Every transition goes
through the forced-checkpoint path the resilience layer already
proves: SIGTERM → the trainer checkpoints at the next step boundary
and exits ``RESILIENCE.PREEMPT_EXIT_CODE`` (77) → relaunch at the
decided topology → elastic resume reshards the restore.  The operator
never kills a trainer any other way.

Two actuation modes:

- ``--mode local`` — the operator owns a ``python -m eksml_tpu.train``
  child: SIGTERM / wait / relaunch with the target topology's
  ``--config`` overrides (and, under ``--fake-chips``, the XLA
  host-platform device-count flag — the chaos rig's topology knob).
  This is the ``proc-capacity-wave`` chaos rung's subject and the
  single-box dev loop.
- ``--mode kubectl`` — in-cluster sidecar/CronJob: the transition is a
  JobSet annotation patch (recording the decided topology) plus a
  graceful pod deletion; kubelet delivers the SIGTERM, the chart's
  podFailurePolicy maps exit 77 to restart-not-fail, and the relaunch
  resumes elastically.  The serve fleet scales through
  ``kubectl scale`` off the scraped ``eksml_serve_queue_depth`` — the
  ACTIVE half of charts/serve's HPA for clusters without a
  prometheus-adapter.

Capacity providers: ``--capacity-file`` (JSON
``{"available_chips": N, "preemption_forecast": 0.x}`` — the local
stub and the chaos rung's wave driver), ``--capacity-env``
(``EKSML_AVAILABLE_CHIPS``), or kubectl (sums the TPU-allocatable of
Ready nodes).  A torn/missing signal is a recorded hold, never a
crash.

Evidence trail (the goodput ledger's downtime buckets show what the
operator saved versus waiting dead):

- flight events ``scale_launch`` / ``scale_decision`` /
  ``scale_hold`` / ``scale_relaunch`` → ``<logdir>/events-hostop.jsonl``
  (merged into run_report's timeline next to the trainer's own);
- ``eksml_autoscale_*`` counters/gauges on the operator's own
  ``/metrics`` (port 0 → ``<logdir>/telemetry-operator.port``),
  preregistered at start so a healthy first scrape shows 0s;
- every decision banked to ``<logdir>/autoscale-host<i>.jsonl`` —
  ``tools/run_report.py``'s "Autoscaling" section joins it against
  the goodput ledger.

Usage::

    python tools/eksml_operator.py --logdir /efs/train_log/run1 \\
        --mode kubectl --jobset maskrcnn --namespace kubeflow \\
        --config RESILIENCE.AUTOSCALE.CHIP_OPTIONS="(16,32)"
    python tools/eksml_operator.py --logdir /tmp/run --mode local \\
        --capacity-file /tmp/capacity.json --fake-chips \\
        --global-batch 8 --train-config TRAIN.SHARDING.STRATEGY=fsdp
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import re
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from eksml_tpu.config import (RESILIENCE_AUTOSCALE_DEFAULTS,  # noqa: E402
                              SHARDING_DEFAULTS, config,
                              knobs_with_defaults)
from eksml_tpu.resilience.autoscale import (ACTIONS,  # noqa: E402
                                            CapacitySignal,
                                            HealthSignal, PolicyParams,
                                            PolicyState, ScaleDecision,
                                            Topology, decide,
                                            serve_replicas,
                                            topology_ladder)
from eksml_tpu.telemetry.exporter import TelemetryExporter  # noqa: E402
from eksml_tpu.telemetry.recorder import FlightRecorder  # noqa: E402
from eksml_tpu.telemetry.registry import MetricRegistry  # noqa: E402

log = logging.getLogger("eksml_operator")

# the operator's flight events land in their own per-"host" file —
# run_report merges every events-host*.jsonl by time, while the
# goodput ledger keeps reading the trainer's events-host0.jsonl
# unpolluted (two processes never append to one file)
OPERATOR_HOST = "op"


# ---------------------------------------------------------------------
# capacity providers (pluggable; every failure degrades to None)
# ---------------------------------------------------------------------


class FileCapacityProvider:
    """JSON file stub: the local/dev signal and the chaos rung's wave
    driver.  ``{"available_chips": 8, "preemption_forecast": 0.1}``."""

    def __init__(self, path: str):
        self.path = path

    def read(self) -> Optional[CapacitySignal]:
        try:
            with open(self.path) as f:
                doc = json.load(f)
            return CapacitySignal(
                int(doc["available_chips"]),
                float(doc.get("preemption_forecast", 0.0)))
        except (OSError, ValueError, TypeError, KeyError):
            return None  # torn mid-rewrite or absent: a recorded hold


class EnvCapacityProvider:
    """``EKSML_AVAILABLE_CHIPS`` / ``EKSML_PREEMPTION_FORECAST``."""

    def __init__(self, var: str = "EKSML_AVAILABLE_CHIPS",
                 forecast_var: str = "EKSML_PREEMPTION_FORECAST"):
        self.var, self.forecast_var = var, forecast_var

    def read(self) -> Optional[CapacitySignal]:
        raw = os.environ.get(self.var)
        if raw is None:
            return None
        try:
            return CapacitySignal(
                int(raw),
                float(os.environ.get(self.forecast_var, "0") or 0))
        except ValueError:
            return None


class KubectlCapacityProvider:
    """Sum the TPU-allocatable of Ready nodes (optionally filtered by
    a label selector) — the in-cluster signal.  No forecast: node
    pools don't publish one; wire a file provider next to it when the
    capacity market does."""

    def __init__(self, resource: str = "google.com/tpu",
                 selector: str = "", kubectl: str = "kubectl",
                 timeout: float = 30.0):
        self.resource = resource
        self.selector = selector
        self.kubectl = kubectl
        self.timeout = timeout

    def command(self) -> List[str]:
        cmd = [self.kubectl, "get", "nodes", "-o", "json"]
        if self.selector:
            cmd += ["-l", self.selector]
        return cmd

    @staticmethod
    def _node_ready(node: Dict) -> bool:
        for cond in node.get("status", {}).get("conditions", []):
            if cond.get("type") == "Ready":
                return cond.get("status") == "True"
        return False

    def parse(self, doc: Dict) -> Optional[CapacitySignal]:
        total = 0
        for node in doc.get("items", []):
            if not self._node_ready(node):
                continue
            alloc = node.get("status", {}).get("allocatable", {})
            try:
                total += int(alloc.get(self.resource, 0))
            except (TypeError, ValueError):
                continue
        return CapacitySignal(total)

    def read(self) -> Optional[CapacitySignal]:
        try:
            out = subprocess.run(
                self.command(), capture_output=True, text=True,
                timeout=self.timeout, check=False)
            if out.returncode != 0:
                return None
            return self.parse(json.loads(out.stdout))
        except (OSError, subprocess.TimeoutExpired,
                json.JSONDecodeError):
            return None


# ---------------------------------------------------------------------
# /metrics scrape → HealthSignal
# ---------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_openmetrics(text: str
                      ) -> Dict[str, List[Tuple[Dict[str, str], float]]]:
    """Exposition text → ``{name: [(labels, value), ...]}`` — just
    enough parser for the operator's own scrapes (the exporter's
    output is the strict side of this contract)."""
    out: Dict[str, List[Tuple[Dict[str, str], float]]] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, labels_raw, value_raw = m.groups()
        try:
            value = float(value_raw)
        except ValueError:
            continue
        labels = {k: v for k, v in _LABEL_RE.findall(labels_raw or "")}
        out.setdefault(name, []).append((labels, value))
    return out


def health_from_metrics(
        families: Dict[str, List[Tuple[Dict[str, str], float]]]
) -> HealthSignal:
    """The exporter series the policy consumes, tolerant of partial
    exposition (an old trainer without the goodput ledger scrapes to
    an all-defaults signal)."""
    ratio = None
    for _labels, v in families.get("eksml_goodput_ratio", []):
        ratio = v
    badput = {labels.get("bucket", ""): v for labels, v in
              families.get("eksml_badput_seconds_total", [])}
    preempt = sum(v for _l, v in families.get(
        "eksml_resilience_preemptions_total", []))
    straggler = 0.0
    for name, samples in families.items():
        if name.startswith("eksml_hosts_") and name.endswith(
                "_straggler"):
            straggler = max([straggler] + [v for _l, v in samples])
    return HealthSignal(goodput_ratio=ratio, badput_s=badput,
                        preemptions=preempt, stragglers=straggler)


def scrape_url(url: str, timeout: float = 5.0) -> Optional[str]:
    import urllib.request

    try:
        return urllib.request.urlopen(
            url, timeout=timeout).read().decode()
    except (OSError, ValueError):
        return None


def trainer_metrics_url(logdir: str, host: int = 0) -> Optional[str]:
    """The trainer's ephemeral-port discovery contract
    (TELEMETRY.PORT=0 → ``telemetry-host<i>.port``).  A stale file
    from the previous segment scrapes to a connection error, which
    degrades to an unknown HealthSignal — correct mid-relaunch."""
    path = os.path.join(logdir, f"telemetry-host{host}.port")
    try:
        with open(path) as f:
            return f"http://127.0.0.1:{int(f.read().strip())}/metrics"
    except (OSError, ValueError):
        return None


# ---------------------------------------------------------------------
# actuators
# ---------------------------------------------------------------------


class LocalTrainerActuator:
    """Owns one ``python -m eksml_tpu.train`` child: the single-box
    actuation path (and the chaos rung's).  Child stdout goes to a
    FILE (an undrained pipe deadlocks the child mid-compile — the
    chaos-ladder lesson)."""

    def __init__(self, logdir: str, train_config: Sequence[str],
                 global_batch: int = 0, fake_chips: bool = False,
                 synthetic: bool = False,
                 extra_env: Optional[Dict[str, str]] = None):
        self.logdir = logdir
        self.train_config = list(train_config)
        self.global_batch = int(global_batch)
        self.fake_chips = fake_chips
        self.synthetic = synthetic
        self.extra_env = dict(extra_env or {})
        self.launches = 0
        self._proc: Optional[subprocess.Popen] = None

    def command(self, topology: Topology) -> List[str]:
        cmd = [sys.executable, "-m", "eksml_tpu.train",
               "--logdir", self.logdir]
        if self.synthetic:
            cmd.append("--synthetic")
        cmd += ["--config"] + self.train_config + list(
            topology.config_overrides(self.global_batch))
        return cmd

    def environment(self, topology: Topology) -> Dict[str, str]:
        env = dict(os.environ)
        env.update(self.extra_env)
        if self.fake_chips:
            # substitute ONLY the device-count flag; other inherited
            # XLA_FLAGS must reach the child unchanged or relaunches
            # run under a different XLA config than the first segment
            kept = [f for f in env.get("XLA_FLAGS", "").split()
                    if "xla_force_host_platform_device_count" not in f]
            kept.append("--xla_force_host_platform_device_count="
                        f"{topology.chips}")
            env["XLA_FLAGS"] = " ".join(kept)
        return env

    def launch(self, topology: Topology) -> str:
        self.launches += 1
        log_path = os.path.join(
            self.logdir, f"operator-train-{self.launches}.log")
        with open(log_path, "a") as logf:  # child inherits the fd
            self._proc = subprocess.Popen(
                self.command(topology),
                env=self.environment(topology), stdout=logf,
                stderr=subprocess.STDOUT, cwd=REPO)
        return log_path

    def poll(self) -> Optional[int]:
        """Child exit code, or None while it runs (or before launch)."""
        if self._proc is None:
            return None
        return self._proc.poll()

    @property
    def running(self) -> bool:
        return self._proc is not None and self._proc.poll() is None

    def stop(self, budget: float = 600.0) -> Optional[int]:
        """SIGTERM → wait: the forced-checkpoint path.  Escalates to
        SIGKILL only past ``budget`` (the chart's
        terminationGracePeriodSeconds analogue)."""
        if self._proc is None:
            return None
        if self._proc.poll() is None:
            self._proc.send_signal(signal.SIGTERM)
            try:
                self._proc.wait(timeout=budget)
            except subprocess.TimeoutExpired:
                log.warning("trainer ignored SIGTERM for %.0fs — "
                            "SIGKILL", budget)
                self._proc.kill()
        if self._proc.poll() is None:  # reap the SIGKILLed child
            try:
                self._proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                pass
        rc = self._proc.poll()
        self._proc = None
        return rc


def kubectl_transition_cmds(jobset: str, namespace: str,
                            topology: Topology, global_batch: int = 0,
                            kubectl: str = "kubectl") -> List[List[str]]:
    """The in-cluster transition: annotate the JobSet with the decided
    topology (the relaunch contract the chart's restart consumes),
    then delete its pods GRACEFULLY — kubelet delivers SIGTERM inside
    terminationGracePeriodSeconds, the trainer forces a checkpoint and
    exits 77, and podFailurePolicy restarts the JobSet instead of
    failing it."""
    overrides = " ".join(topology.config_overrides(global_batch))
    patch = json.dumps({"metadata": {"annotations": {
        "eksml.dev/target-topology": topology.name,
        "eksml.dev/target-chips": str(topology.chips),
        "eksml.dev/target-config": overrides}}})
    return [
        [kubectl, "-n", namespace, "patch", "jobset", jobset,
         "--type", "merge", "-p", patch],
        [kubectl, "-n", namespace, "delete", "pod",
         "-l", f"jobset.sigs.k8s.io/jobset-name={jobset}",
         "--wait=false"],
    ]


def kubectl_serve_scale_cmd(deployment: str, namespace: str,
                            replicas: int,
                            kubectl: str = "kubectl") -> List[str]:
    return [kubectl, "-n", namespace, "scale",
            f"deployment/{deployment}", f"--replicas={int(replicas)}"]


# ---------------------------------------------------------------------
# canary promotion controller (the continuous-deployment gate)
# ---------------------------------------------------------------------

# the controller's flight events get their own per-"host" file for the
# same reason the operator does: two processes never append to one
PROMOTER_HOST = "cd"


def promotion_verdict(score: Dict, knobs: Dict) -> Tuple[str, str]:
    """Pure decision: one shadow score → (verdict, reason).

    Asymmetric by design — **rollback is immediate** (one breached
    gate demotes the canary; a regressed checkpoint must leave live
    traffic NOW), **promotion is patient** (the caller requires
    ``CANARY_PROMOTE_STREAK`` consecutive ``promote`` verdicts before
    flipping the incumbent, so one lucky replay cannot promote).  An
    unscorable replay (too few pairs, no latency baseline) holds:
    never promote OR demote on evidence that thin."""
    scored = int(score.get("scored") or 0)
    min_req = int(knobs["CANARY_MIN_REQUESTS"])
    err_rate = score.get("canary_error_rate")
    # error rate is judged even below the scoring floor: a canary
    # failing every request scores zero pairs and would otherwise
    # hold forever instead of rolling back
    if err_rate is not None \
            and float(err_rate) > float(knobs["CANARY_ERROR_RATE_MAX"]):
        return ("rollback",
                f"canary error rate {err_rate} > "
                f"{knobs['CANARY_ERROR_RATE_MAX']}")
    if scored < min_req:
        return ("hold",
                f"only {scored} scored pair(s) < CANARY_MIN_REQUESTS="
                f"{min_req} — not enough evidence either way")
    ratio = score.get("p99_ratio")
    if ratio is not None \
            and float(ratio) > float(knobs["CANARY_P99_RATIO_MAX"]):
        return ("rollback",
                f"canary p99 {ratio}x incumbent > "
                f"{knobs['CANARY_P99_RATIO_MAX']}x")
    drift = (score.get("drift") or {}).get("mean")
    if drift is None or ratio is None:
        return "hold", "replay unscorable (missing drift/latency axis)"
    if float(drift) > float(knobs["CANARY_DRIFT_MAX"]):
        return ("rollback",
                f"output drift {drift} > {knobs['CANARY_DRIFT_MAX']} "
                "— the canary checkpoint disagrees with the "
                "incumbent beyond the gate")
    return ("promote",
            f"all gates passed (p99_ratio={ratio}, "
            f"error_rate={err_rate}, drift={drift})")


def post_reload(url: str, step: Optional[int] = None,
                timeout: float = 300.0) -> Dict:
    """``POST /admin/reload`` — the controller's demote/promote lever.
    Answers the server's outcome dict; transport failures degrade to
    ``{"ok": False, ...}`` (the controller records, never crashes)."""
    import urllib.error
    import urllib.request

    body = json.dumps({} if step is None
                      else {"step": int(step)}).encode("utf-8")
    req = urllib.request.Request(
        url.rstrip("/") + "/admin/reload", data=body,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        try:
            return json.loads(e.read().decode("utf-8"))
        except Exception:  # noqa: BLE001 — non-JSON error body
            return {"ok": False, "reason": "http", "detail": repr(e)}
    except (OSError, ValueError) as e:
        return {"ok": False, "reason": "unreachable", "detail": repr(e)}


class PromotionController:
    """Shadow-score the canary each tick; promote or roll back.

    One tick = read both ``/healthz`` (which checkpoint is each track
    serving?) → replay the banked traffic at both (``replay_shadow``)
    → ``promotion_verdict`` → actuate via ``/admin/reload``:

    - **rollback**: the canary reloads the INCUMBENT's step —
      immediately, on the first breached gate;
    - **promote**: after ``CANARY_PROMOTE_STREAK`` consecutive clean
      scores, the incumbent reloads the CANARY's step (the canary
      Deployment keeps serving it — promotion converges the fleet).

    Every score/verdict lands in ``<logdir>/canary-host<id>.jsonl``,
    flight events (``canary_score`` / ``canary_promote`` /
    ``canary_rollback``) in ``events-host{PROMOTER_HOST}.jsonl``, and
    the ``eksml_serve_canary_*`` series on the controller's exporter —
    run_report's Deployments section replays the whole timeline."""

    def __init__(self, logdir: str, incumbent_url: str,
                 canary_url: str, bank: Dict, knobs: Dict,
                 registry: Optional[MetricRegistry] = None,
                 recorder: Optional[FlightRecorder] = None,
                 raw_topk: int = 16, concurrency: int = 4,
                 timeout: float = 120.0):
        self.logdir = logdir
        self.incumbent_url = incumbent_url
        self.canary_url = canary_url
        self.bank = bank
        self.knobs = knobs
        self.raw_topk = int(raw_topk)
        self.concurrency = int(concurrency)
        self.timeout = float(timeout)
        self.streak = 0
        self.promotions = 0
        self.rollbacks = 0
        self.bank_path = os.path.join(logdir, "canary-host0.jsonl")
        self.bank_failures = 0
        self.registry = registry or MetricRegistry()
        self._preregister(self.registry)
        self.recorder = recorder or FlightRecorder(
            capacity=256,
            path=os.path.join(logdir,
                              f"events-host{PROMOTER_HOST}.jsonl"),
            host_id=PROMOTER_HOST)

    @staticmethod
    def _preregister(registry: MetricRegistry) -> None:
        registry.counter("eksml_serve_canary_scores",
                         "shadow-replay scoring rounds completed")
        for verdict in ("promote", "rollback", "hold"):
            registry.counter("eksml_serve_canary_verdicts",
                             "promotion verdicts by outcome",
                             labels={"verdict": verdict})
        registry.counter("eksml_serve_canary_promotions",
                         "canary checkpoints promoted to the "
                         "incumbent track")
        registry.counter("eksml_serve_canary_rollbacks",
                         "regressed canaries demoted back to the "
                         "incumbent checkpoint")
        registry.gauge("eksml_serve_canary_p99_ratio",
                       "latest canary/incumbent latency p99 ratio")
        registry.gauge("eksml_serve_canary_error_rate",
                       "latest canary error rate over the shadow "
                       "replay")
        registry.gauge("eksml_serve_canary_drift",
                       "latest mean detection-output drift vs the "
                       "incumbent")

    @staticmethod
    def _loadtest():
        """The scoring engine is serve_loadtest.py itself — one
        replay/drift definition for the CLI and the controller."""
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import serve_loadtest
        return serve_loadtest

    def _bank_row(self, row: Dict) -> None:
        row = dict(row)
        row.setdefault("time", time.time())
        try:
            with open(self.bank_path, "a") as f:
                f.write(json.dumps(row) + "\n")
        except (OSError, TypeError, ValueError):
            self.bank_failures += 1

    def tick(self) -> Dict:
        """One scoring round; returns ``{"verdict": ..., ...}``."""
        lt = self._loadtest()
        try:
            inc = lt.fetch_health(self.incumbent_url,
                                  timeout=self.timeout)
            can = lt.fetch_health(self.canary_url,
                                  timeout=self.timeout)
        except (OSError, ValueError) as e:
            return self._hold(f"health unreachable: {e!r}")
        inc_step, can_step = inc.get("params_step"), \
            can.get("params_step")
        if can.get("status") != "ok" or inc.get("status") != "ok":
            return self._hold(
                f"track not serving (incumbent={inc.get('status')}, "
                f"canary={can.get('status')})")
        if can_step is None or can_step == inc_step:
            # converged fleet: nothing to score until training
            # publishes a new checkpoint and the canary picks it up
            return self._hold(
                f"tracks converged at step {inc_step} — no candidate")
        score = lt.replay_shadow(self.bank, self.incumbent_url,
                                 self.canary_url,
                                 timeout=self.timeout,
                                 raw_topk=self.raw_topk,
                                 concurrency=self.concurrency)
        self.registry.counter("eksml_serve_canary_scores", "").inc()
        if score.get("p99_ratio") is not None:
            self.registry.gauge("eksml_serve_canary_p99_ratio",
                                "").set(float(score["p99_ratio"]))
        self.registry.gauge("eksml_serve_canary_error_rate",
                            "").set(float(score["canary_error_rate"]))
        drift = (score.get("drift") or {}).get("mean")
        if drift is not None:
            self.registry.gauge("eksml_serve_canary_drift",
                                "").set(float(drift))
        verdict, reason = promotion_verdict(score, self.knobs)
        self.registry.counter("eksml_serve_canary_verdicts", "",
                              labels={"verdict": verdict}).inc()
        self.recorder.record(
            "canary_score", verdict=verdict, reason=reason,
            incumbent_step=inc_step, canary_step=can_step,
            p99_ratio=score.get("p99_ratio"),
            error_rate=score.get("canary_error_rate"), drift=drift)
        outcome = {"verdict": verdict, "reason": reason,
                   "incumbent_step": inc_step,
                   "canary_step": can_step, "score": score}
        if verdict == "rollback":
            self.streak = 0
            self.rollbacks += 1
            self.registry.counter("eksml_serve_canary_rollbacks",
                                  "").inc()
            demote = post_reload(self.canary_url, step=inc_step,
                                 timeout=self.timeout)
            self.recorder.record(
                "canary_rollback", reason=reason,
                from_step=can_step, to_step=inc_step,
                reload_ok=bool(demote.get("ok")))
            log.warning("canary ROLLED BACK (step %s -> %s): %s",
                        can_step, inc_step, reason)
            outcome["reload"] = demote
        elif verdict == "promote":
            self.streak += 1
            streak_need = int(self.knobs["CANARY_PROMOTE_STREAK"])
            if self.streak >= streak_need:
                self.promotions += 1
                self.registry.counter(
                    "eksml_serve_canary_promotions", "").inc()
                promote = post_reload(self.incumbent_url,
                                      step=can_step,
                                      timeout=self.timeout)
                self.recorder.record(
                    "canary_promote", step=can_step,
                    previous_step=inc_step, streak=self.streak,
                    reload_ok=bool(promote.get("ok")))
                log.info("canary PROMOTED: incumbent now serves "
                         "step %s (was %s)", can_step, inc_step)
                outcome["reload"] = promote
                self.streak = 0
            else:
                outcome["reason"] += (f"; streak {self.streak}/"
                                      f"{streak_need} — promotion "
                                      "needs more clean scores")
        else:
            self.streak = 0
        self._bank_row({"kind": "canary_verdict", **{
            k: outcome[k] for k in ("verdict", "reason",
                                    "incumbent_step", "canary_step")},
            "p99_ratio": score.get("p99_ratio"),
            "error_rate": score.get("canary_error_rate"),
            "drift": drift, "streak": self.streak})
        return outcome

    def _hold(self, reason: str) -> Dict:
        self.registry.counter("eksml_serve_canary_verdicts", "",
                              labels={"verdict": "hold"}).inc()
        self._bank_row({"kind": "canary_verdict", "verdict": "hold",
                        "reason": reason})
        return {"verdict": "hold", "reason": reason}

    def run(self, interval: float, stop_flag, max_ticks: int = 0,
            once: bool = False) -> int:
        ticks = 0
        while not stop_flag.stop:
            out = self.tick()
            log.info("canary tick %d: %s (%s)", ticks,
                     out["verdict"], out["reason"])
            ticks += 1
            if once or (max_ticks and ticks >= max_ticks):
                break
            deadline = time.monotonic() + max(0.5, interval)
            while not stop_flag.stop \
                    and time.monotonic() < deadline:
                time.sleep(0.2)
        return 0


# ---------------------------------------------------------------------
# the operator loop
# ---------------------------------------------------------------------


class _StopFlag:
    """SIGTERM/SIGINT land here flag-only (signal-safety rule: a
    handler runs between bytecodes on the interrupted thread — no
    locks, no logging, no metric publishes)."""

    def __init__(self):
        self.stop = False

    def __call__(self, signum, frame):
        self.stop = True


class Operator:
    def __init__(self, args, knobs: Dict, ladder: Sequence[Topology],
                 provider, registry: Optional[MetricRegistry] = None,
                 actuator: Optional[LocalTrainerActuator] = None):
        self.args = args
        self.knobs = knobs
        self.ladder = tuple(ladder)
        self.provider = provider
        self.actuator = actuator
        self.params = PolicyParams(
            cooldown_sec=float(knobs["COOLDOWN_SEC"]),
            grow_patience=int(knobs["GROW_PATIENCE"]),
            shrink_patience=int(knobs["SHRINK_PATIENCE"]),
            forecast_hold=float(knobs["FORECAST_HOLD"]),
            min_goodput_for_grow=float(knobs["MIN_GOODPUT_FOR_GROW"]))
        self.state: Optional[PolicyState] = None
        self.stop_flag = _StopFlag()
        self.bank_path = os.path.join(
            args.logdir, f"autoscale-host{args.operator_id}.jsonl")
        self.bank_failures = 0
        self.restarts = 0
        self.serve_target: Optional[int] = None

        self.registry = registry or MetricRegistry()
        self._preregister(self.registry)
        self.recorder = FlightRecorder(
            capacity=256,
            path=os.path.join(args.logdir,
                              f"events-host{OPERATOR_HOST}.jsonl"),
            host_id=OPERATOR_HOST)
        self.exporter = TelemetryExporter(
            port=args.port, registry=self.registry,
            port_file=os.path.join(args.logdir,
                                   "telemetry-operator.port"))

    # -- satellite 1: the PR-4 preregistration convention -------------
    @staticmethod
    def _preregister(registry: MetricRegistry) -> None:
        """Create every eksml_autoscale_* series at operator start so
        a healthy first scrape shows the whole family at 0."""
        for action in ACTIONS:
            registry.counter(
                "eksml_autoscale_decisions",
                "scale decisions by action", labels={"action": action})
        registry.gauge(
            "eksml_autoscale_target_chips",
            "chip count of the currently-decided topology")
        registry.gauge(
            "eksml_autoscale_available_chips",
            "capacity provider's latest available-chip reading")
        registry.counter(
            "eksml_autoscale_relaunches",
            "trainer relaunches driven through the forced-checkpoint "
            "path")
        registry.counter(
            "eksml_autoscale_capacity_errors",
            "ticks whose capacity signal was unreadable")
        registry.gauge(
            "eksml_autoscale_serve_target_replicas",
            "desired serve replicas (the active half of the serve "
            "HPA)")

    # -- evidence trail ------------------------------------------------
    def _bank(self, row: Dict) -> None:
        row = dict(row)
        row.setdefault("time", time.time())
        try:
            with open(self.bank_path, "a") as f:
                f.write(json.dumps(row) + "\n")
        except (OSError, TypeError, ValueError):
            self.bank_failures += 1

    def _record_decision(self, decision: ScaleDecision,
                         capacity: Optional[CapacitySignal],
                         health: HealthSignal) -> None:
        self.registry.counter(
            "eksml_autoscale_decisions", "",
            labels={"action": decision.action}).inc()
        self.registry.gauge("eksml_autoscale_target_chips",
                            "").set(decision.target.chips)
        if capacity is not None:
            self.registry.gauge("eksml_autoscale_available_chips",
                                "").set(capacity.available_chips)
        row = decision.to_dict()
        row["kind"] = "decision"
        if capacity is not None:
            row["available_chips"] = capacity.available_chips
            row["preemption_forecast"] = capacity.preemption_forecast
        if health.goodput_ratio is not None:
            row["goodput_ratio"] = round(health.goodput_ratio, 4)
        self._bank(row)
        event_kind = ("scale_hold" if decision.action == "hold"
                      else "scale_decision")
        self.recorder.record(event_kind, action=decision.action,
                             target=decision.target.name,
                             target_chips=decision.target.chips,
                             reason=decision.reason)

    # -- health --------------------------------------------------------
    def _scrape_health(self) -> HealthSignal:
        url = trainer_metrics_url(self.args.logdir)
        text = scrape_url(url) if url else None
        if text is None:
            return HealthSignal()
        return health_from_metrics(parse_openmetrics(text))

    # -- actuation -----------------------------------------------------
    def _actuate(self, decision: ScaleDecision) -> None:
        target = decision.target
        if self.args.mode == "local":
            assert self.actuator is not None
            t0 = time.time()
            rc = self.actuator.stop(budget=self.args.stop_budget)
            stopped_t = time.time()
            self.actuator.launch(target)
            self.registry.counter("eksml_autoscale_relaunches",
                                  "").inc()
            self.recorder.record(
                "scale_relaunch", action=decision.action,
                target=target.name, target_chips=target.chips,
                exit_code=rc,
                relaunch_gap_s=round(time.time() - stopped_t, 3))
            self._bank({"kind": "relaunch", "action": decision.action,
                        "target": target.name,
                        "target_chips": target.chips, "exit_code": rc,
                        "stop_s": round(stopped_t - t0, 3),
                        "relaunch_gap_s":
                            round(time.time() - stopped_t, 3)})
            return
        # kubectl mode: the graceful-deletion transition
        cmds = kubectl_transition_cmds(
            self.args.jobset, self.args.namespace, target,
            self.args.global_batch, kubectl=self.args.kubectl)
        rcs = [self._run_kubectl(c) for c in cmds]
        self.registry.counter("eksml_autoscale_relaunches", "").inc()
        self.recorder.record("scale_relaunch", action=decision.action,
                             target=target.name,
                             target_chips=target.chips,
                             kubectl_rcs=rcs)
        self._bank({"kind": "relaunch", "action": decision.action,
                    "target": target.name,
                    "target_chips": target.chips,
                    "kubectl_rcs": rcs})

    def _run_kubectl(self, cmd: List[str]) -> int:
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=self.args.kubectl_timeout,
                                 check=False)
            if out.returncode != 0:
                log.warning("kubectl failed (%d): %s\n%s",
                            out.returncode, " ".join(cmd),
                            out.stderr[-500:])
            return out.returncode
        except (OSError, subprocess.TimeoutExpired) as e:
            log.warning("kubectl errored: %s (%s)", " ".join(cmd), e)
            return -1

    # -- serve fleet (active half of the charts/serve HPA) ------------
    def _scale_serve(self) -> None:
        target_depth = float(self.knobs["SERVE_TARGET_QUEUE_DEPTH"])
        if target_depth <= 0 or not self.args.serve_metrics_url:
            return
        text = scrape_url(self.args.serve_metrics_url)
        if text is None:
            return
        fams = parse_openmetrics(text)
        depths = [v for _l, v in fams.get("eksml_serve_queue_depth",
                                          [])]
        if not depths:
            return
        depth = sum(depths) / len(depths)
        current = (self.serve_target
                   or int(self.knobs["SERVE_MIN_REPLICAS"]))
        desired = serve_replicas(
            depth, current, target_depth,
            int(self.knobs["SERVE_MIN_REPLICAS"]),
            int(self.knobs["SERVE_MAX_REPLICAS"]))
        self.registry.gauge("eksml_autoscale_serve_target_replicas",
                            "").set(desired)
        if desired == self.serve_target:
            return
        self.serve_target = desired
        self.recorder.record("scale_serve", replicas=desired,
                             queue_depth=round(depth, 2))
        self._bank({"kind": "serve_scale", "replicas": desired,
                    "queue_depth": round(depth, 2)})
        if self.args.mode == "kubectl" and self.args.serve_deployment:
            self._run_kubectl(kubectl_serve_scale_cmd(
                self.args.serve_deployment, self.args.namespace,
                desired, kubectl=self.args.kubectl))

    # -- lifecycle -----------------------------------------------------
    def _initial_topology(self,
                          capacity: Optional[CapacitySignal]
                          ) -> Topology:
        if self.args.initial_chips:
            for topo in self.ladder:
                if topo.chips == self.args.initial_chips:
                    return topo
            raise SystemExit(
                f"--initial-chips {self.args.initial_chips} names no "
                f"ladder rung (have "
                f"{[t.chips for t in self.ladder]})")
        if capacity is not None:
            for topo in reversed(self.ladder):
                if topo.chips <= capacity.available_chips:
                    return topo
        return self.ladder[-1]

    def start(self) -> None:
        self.exporter.start()
        capacity = self.provider.read()
        topo = self._initial_topology(capacity)
        now = time.time()
        self.state = PolicyState(topo, last_change_t=now)
        self.registry.gauge("eksml_autoscale_target_chips",
                            "").set(topo.chips)
        if self.args.mode == "local" and self.actuator is not None:
            log_path = self.actuator.launch(topo)
            log.info("launched trainer at %s (%d chips) → %s",
                     topo.name, topo.chips, log_path)
        self.recorder.record("scale_launch", target=topo.name,
                             target_chips=topo.chips)
        self._bank({"kind": "launch", "target": topo.name,
                    "target_chips": topo.chips})

    def _child_watch(self) -> bool:
        """Local-mode child supervision between decisions.  Returns
        False when the operator should exit (training completed or
        the restart budget is spent)."""
        if self.args.mode != "local" or self.actuator is None:
            return True
        rc = self.actuator.poll()
        if rc is None:
            return True
        if rc == 0:
            log.info("trainer completed (exit 0) — operator done")
            self.recorder.record("train_complete", exit_code=0)
            self._bank({"kind": "train_complete", "exit_code": 0})
            return False
        # a crash (or an externally-delivered preemption): relaunch at
        # the CURRENT topology, bounded like JobSet maxRestarts
        self.restarts += 1
        if self.restarts > self.args.max_restarts:
            log.error("trainer exit %d and restart budget (%d) spent",
                      rc, self.args.max_restarts)
            self._bank({"kind": "restart_budget_spent",
                        "exit_code": rc})
            return False
        assert self.state is not None
        topo = self.state.topology
        self.actuator.launch(topo)
        self.registry.counter("eksml_autoscale_relaunches", "").inc()
        self.recorder.record("scale_relaunch", action="restart",
                             target=topo.name,
                             target_chips=topo.chips, exit_code=rc)
        self._bank({"kind": "relaunch", "action": "restart",
                    "target": topo.name, "target_chips": topo.chips,
                    "exit_code": rc})
        return True

    def tick(self) -> None:
        now = time.time()
        capacity = self.provider.read()
        health = self._scrape_health()
        if capacity is None:
            self.registry.counter("eksml_autoscale_capacity_errors",
                                  "").inc()
            assert self.state is not None
            decision = ScaleDecision(
                "hold", self.state.topology,
                "capacity signal unavailable")
            self._record_decision(decision, None, health)
        else:
            assert self.state is not None
            decision, self.state = decide(
                self.state, capacity, health, self.ladder,
                self.params, now)
            self._record_decision(decision, capacity, health)
            if decision.action != "hold":
                self._actuate(decision)
        self._scale_serve()

    def run(self) -> int:
        self.start()
        interval = float(self.args.interval
                         or self.knobs["INTERVAL_SEC"])
        ticks = 0
        try:
            while not self.stop_flag.stop:
                if not self._child_watch():
                    break
                self.tick()
                ticks += 1
                if self.args.once or (self.args.max_ticks
                                      and ticks >= self.args.max_ticks):
                    break
                deadline = time.time() + interval
                while (time.time() < deadline
                       and not self.stop_flag.stop):
                    time.sleep(min(
                        0.2, max(0.0, deadline - time.time())))
        finally:
            if self.args.mode == "local" and self.actuator is not None:
                rc = self.actuator.stop(budget=self.args.stop_budget)
                if rc is not None:
                    self.recorder.record("scale_stop", exit_code=rc)
                    self._bank({"kind": "stop", "exit_code": rc})
            self.exporter.stop()
        return 0


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--logdir", required=True,
                   help="training run directory (evidence trail + "
                        "local-mode trainer logdir)")
    p.add_argument("--mode", choices=("local", "kubectl"),
                   default="local")
    p.add_argument("--config", nargs="*", default=[],
                   help="config overrides, e.g. "
                        "RESILIENCE.AUTOSCALE.COOLDOWN_SEC=120")
    p.add_argument("--capacity-file", default=None,
                   help="JSON capacity stub "
                        '{"available_chips": N, ...}')
    p.add_argument("--capacity-env", action="store_true",
                   help="read capacity from EKSML_AVAILABLE_CHIPS")
    p.add_argument("--capacity-selector", default="",
                   help="kubectl node label selector for the "
                        "capacity census")
    p.add_argument("--capacity-resource", default="google.com/tpu",
                   help="allocatable resource counted as chips")
    p.add_argument("--interval", type=float, default=0.0,
                   help="tick seconds (0 = "
                        "RESILIENCE.AUTOSCALE.INTERVAL_SEC)")
    p.add_argument("--once", action="store_true",
                   help="single tick then exit (CronJob mode)")
    p.add_argument("--max-ticks", type=int, default=0,
                   help="exit after N ticks (chaos harness bound; "
                        "0 = run until signaled)")
    p.add_argument("--port", type=int, default=0,
                   help="operator /metrics port (0 = ephemeral, "
                        "published to telemetry-operator.port)")
    p.add_argument("--operator-id", type=int, default=0,
                   help="suffix of autoscale-host<i>.jsonl")
    # local mode
    p.add_argument("--train-config", nargs="*", default=[],
                   help="base --config items for the local trainer "
                        "(topology overrides are appended)")
    p.add_argument("--global-batch", type=int, default=0,
                   help="hold chips x per-chip batch at this global "
                        "batch across topologies (0 = leave batch "
                        "knobs alone)")
    p.add_argument("--synthetic", action="store_true",
                   help="pass --synthetic to the local trainer")
    p.add_argument("--fake-chips", action="store_true",
                   help="drive topology via "
                        "xla_force_host_platform_device_count "
                        "(CPU chaos rig)")
    p.add_argument("--initial-chips", type=int, default=0,
                   help="ladder rung to launch at (0 = best fit of "
                        "the first capacity reading)")
    p.add_argument("--stop-budget", type=float, default=600.0,
                   help="seconds a SIGTERMed trainer may take to "
                        "checkpoint before SIGKILL")
    p.add_argument("--max-restarts", type=int, default=10,
                   help="local-mode crash-relaunch budget (the "
                        "JobSet maxRestarts analogue)")
    # canary promotion controller
    p.add_argument("--promote", action="store_true",
                   help="run the canary promotion controller instead "
                        "of the autoscale loop: shadow-score the "
                        "canary each tick, roll back on a breached "
                        "gate, promote after CANARY_PROMOTE_STREAK "
                        "clean scores")
    p.add_argument("--incumbent-url", default="",
                   help="stable track base URL (--promote)")
    p.add_argument("--canary-url", default="",
                   help="canary track base URL (--promote)")
    p.add_argument("--shadow-bank", default="",
                   help="recorded request bank (serve_loadtest.py "
                        "--record) replayed for scoring (--promote)")
    p.add_argument("--raw-topk", type=int, default=16,
                   help="pre-threshold top-k drift signal depth")
    p.add_argument("--shadow-concurrency", type=int, default=4)
    p.add_argument("--shadow-timeout", type=float, default=120.0)
    # kubectl mode
    p.add_argument("--kubectl", default="kubectl")
    p.add_argument("--kubectl-timeout", type=float, default=60.0)
    p.add_argument("--jobset", default="maskrcnn")
    p.add_argument("--namespace", default="kubeflow")
    p.add_argument("--serve-deployment", default="",
                   help="serve Deployment to scale (kubectl mode)")
    p.add_argument("--serve-metrics-url", default="",
                   help="a serve pod's /metrics URL (queue-depth "
                        "source for the active HPA half)")
    return p


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    args = build_parser().parse_args(argv)
    os.makedirs(args.logdir, exist_ok=True)

    # --train-config is applied too: the operator's ladder must read
    # the SAME sharding strategy the trainer will run under
    config.update_args(list(args.config) + list(args.train_config))
    knobs = knobs_with_defaults(
        getattr(getattr(config, "RESILIENCE", None), "AUTOSCALE",
                None), RESILIENCE_AUTOSCALE_DEFAULTS)

    if args.promote:
        if not (args.incumbent_url and args.canary_url
                and args.shadow_bank):
            raise SystemExit("--promote needs --incumbent-url, "
                             "--canary-url and --shadow-bank")
        with open(args.shadow_bank) as f:
            bank = json.load(f)
        controller = PromotionController(
            args.logdir, args.incumbent_url, args.canary_url, bank,
            knobs, raw_topk=args.raw_topk,
            concurrency=args.shadow_concurrency,
            timeout=args.shadow_timeout)
        exporter = TelemetryExporter(
            port=args.port, registry=controller.registry,
            port_file=os.path.join(args.logdir,
                                   "telemetry-promoter.port"))
        exporter.start()
        stop_flag = _StopFlag()
        signal.signal(signal.SIGTERM, stop_flag)
        signal.signal(signal.SIGINT, stop_flag)
        log.info("promotion controller up: incumbent=%s canary=%s "
                 "bank=%d request(s)", args.incumbent_url,
                 args.canary_url, len(bank.get("requests", ())))
        try:
            return controller.run(
                args.interval or float(knobs["INTERVAL_SEC"]),
                stop_flag, max_ticks=args.max_ticks, once=args.once)
        finally:
            exporter.stop()
    sharding = knobs_with_defaults(
        getattr(getattr(config, "TRAIN", None), "SHARDING", None),
        SHARDING_DEFAULTS)
    chip_options = tuple(
        int(c) for c in (knobs["CHIP_OPTIONS"] or ()))
    if not chip_options:
        raise SystemExit(
            "RESILIENCE.AUTOSCALE.CHIP_OPTIONS is empty — pass "
            '--config RESILIENCE.AUTOSCALE.CHIP_OPTIONS="(4,8)" '
            "(the ladder the operator may scale over)")
    ladder = topology_ladder(
        chip_options, strategy=str(sharding["STRATEGY"]),
        model_axis=int(sharding["MODEL_AXIS_SIZE"]),
        num_slices=max(1, int(getattr(config.TPU, "NUM_SLICES", 1))))
    if not ladder:
        raise SystemExit(
            f"no valid topology for CHIP_OPTIONS={chip_options} "
            f"under strategy {sharding['STRATEGY']!r} — every count "
            "was rejected by the plan_mesh divisibility contract")

    if args.capacity_file:
        provider = FileCapacityProvider(args.capacity_file)
    elif args.capacity_env:
        provider = EnvCapacityProvider()
    elif args.mode == "kubectl":
        provider = KubectlCapacityProvider(
            resource=args.capacity_resource,
            selector=args.capacity_selector, kubectl=args.kubectl,
            timeout=args.kubectl_timeout)
    else:
        raise SystemExit("local mode needs --capacity-file or "
                         "--capacity-env")

    actuator = None
    if args.mode == "local":
        actuator = LocalTrainerActuator(
            args.logdir, args.train_config,
            global_batch=args.global_batch,
            fake_chips=args.fake_chips, synthetic=args.synthetic)

    op = Operator(args, knobs, ladder, provider, actuator=actuator)
    signal.signal(signal.SIGTERM, op.stop_flag)
    signal.signal(signal.SIGINT, op.stop_flag)
    log.info("operator up: ladder=%s interval=%ss mode=%s",
             [t.name for t in ladder],
             args.interval or knobs["INTERVAL_SEC"], args.mode)
    return op.run()


if __name__ == "__main__":
    sys.exit(main())
