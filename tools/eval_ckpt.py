"""Offline COCO evaluation of a training checkpoint.

The CLI twin of the inference notebook (reference role:
container-viz notebook's ckpt-discovery → predict path) and the rerun
path for any banked run: point it at a training ``--logdir`` and a
dataset, it restores the latest (or ``--step``) Orbax checkpoint and
runs the distributed-capable evaluator on the requested split.

Usage::

    python tools/eval_ckpt.py --logdir /tmp/run --data <basedir> \
        [--split val2017] [--max-images N] [--out results.json] \
        [--platform cpu] [--config KEY=VALUE ...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--logdir", required=True)
    p.add_argument("--data", required=True, help="COCO-layout basedir")
    p.add_argument("--split", default="val2017")
    p.add_argument("--step", type=int, default=None,
                   help="checkpoint step (default: latest)")
    p.add_argument("--max-images", type=int, default=None)
    p.add_argument("--out", default=None)
    p.add_argument("--platform", default=None)
    p.add_argument("--config", nargs="*", default=[],
                   help="KEY=VALUE overrides — must match the "
                        "training run's model architecture")
    args = p.parse_args(argv)

    import jax

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from eksml_tpu.utils.compile_cache import enable_persistent_cache

    enable_persistent_cache()

    from eksml_tpu.config import config as cfg
    from eksml_tpu.config import finalize_configs
    from eksml_tpu.data import CocoDataset
    from eksml_tpu.data.loader import make_synthetic_batch
    from eksml_tpu.evalcoco import run_evaluation
    from eksml_tpu.train import Trainer

    cfg.freeze(False)
    cfg.DATA.BASEDIR = args.data
    cfg.TRAIN.LOGDIR = args.logdir
    # the checkpoint supplies every param; loading the pretrained npz
    # (a training-box path) would be wasted I/O and crashes eval boxes
    # that don't have it.  Cleared BEFORE update_args so an explicit
    # --config BACKBONE.WEIGHTS=... still wins (convergence_run.py
    # orders it the same way).
    cfg.BACKBONE.WEIGHTS = ""
    cfg.update_args(args.config)
    finalize_configs(is_training=True)  # trainer state incl. optimizer
    # cfg is the source of truth after update_args: a --config
    # TRAIN.LOGDIR / DATA.BASEDIR override must move the checkpoint
    # read and the dataset together, not leave them on the flags
    logdir = cfg.TRAIN.LOGDIR
    data_dir = cfg.DATA.BASEDIR

    # read-only: never append to the run's metrics.jsonl / TB events
    trainer = Trainer(cfg, logdir, write_metrics=False)
    latest = trainer.ckpt.latest_step()
    if latest is None:
        print("eval_ckpt: no checkpoint found under "
              f"{logdir}/checkpoints", file=sys.stderr)
        return 1
    at_step = latest if args.step is None else args.step
    example = make_synthetic_batch(cfg, batch_size=1,
                                   image_size=cfg.PREPROC.MAX_SIZE)
    # init builds the restore template; exactly ONE checkpoint read
    state = trainer.init_state(trainer._globalize_batch(example))
    try:
        state = trainer.ckpt.restore(state, step=at_step)
        restore_err = None
    except Exception as e:  # noqa: BLE001 — pruned/missing step
        restore_err = e
    # The restore verdict must be ONE decision for the whole fleet:
    # run_evaluation's detection gather below is a collective, and a
    # lone host returning early here (stale NFS handle, pruned step
    # visible to one attribute cache) would leave every other host
    # blocked in the allgather forever — the collective-order class
    # eksml-lint flags statically, fixed by agreeing first.
    if not trainer.ckpt.all_hosts_ok(restore_err is None):
        print(f"eval_ckpt: restore of step {at_step} failed on at "
              f"least one host (local error: {restore_err!r}); "
              f"available: {os.listdir(trainer.ckpt.directory)}",
              file=sys.stderr)
        return 1

    records = CocoDataset(data_dir, args.split).records(skip_empty=False)
    t0 = time.time()
    results = run_evaluation(trainer.model, state.params, cfg, records,
                             max_images=args.max_images)
    payload = {"logdir": logdir, "step": int(at_step),
               "split": args.split,
               "num_images": (min(args.max_images, len(records))
                              if args.max_images else len(records)),
               "eval_seconds": round(time.time() - t0, 1),
               **{k: round(float(v), 4) for k, v in results.items()}}
    print(json.dumps(payload))
    if args.out:
        from eksml_tpu.fsio import atomic_write_json

        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        atomic_write_json(args.out, payload)
    return 0


if __name__ == "__main__":
    sys.exit(main())
