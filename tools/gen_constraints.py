"""Regenerate container/constraints.txt from the live environment.

Walks the transitive dependency closure of the packages the container
images actually install (container/Dockerfile, container-viz/
Dockerfile) and emits exact ``name==version`` pins for every installed
member — the TPU analogue of the reference pinning tensorpack/cocoapi
to commits (reference container/Dockerfile:16-19).  pip constraints
only apply to packages being installed, so closure members a given
image never resolves are inert.

Usage::

    python tools/gen_constraints.py > container/constraints.txt
"""

from __future__ import annotations

import re
from importlib.metadata import PackageNotFoundError, distribution

# the packages named in the Dockerfiles' pip install lines, with the
# extras those lines request — jax[tpu]'s extras-gated deps (libtpu,
# requests) must be pinned through THIS root, not by coincidence via
# an unrelated closure member
ROOTS = [("jax", ("tpu",)), ("jaxlib", ()), ("libtpu", ()),
         ("flax", ()), ("optax", ()), ("orbax-checkpoint", ()),
         ("einops", ()), ("numpy", ()), ("ml_dtypes", ()),
         ("pillow", ()), ("jupyterlab", ()), ("matplotlib", ())]

HEADER = """\
# Pinned engine stack for the training/viz images (VERDICT r3 next #3).
# The reference pins every external component to a commit
# (container/Dockerfile:16-19 tensorpack @db541e8;
# container-optimized/Dockerfile:26-31 mask-rcnn-tensorflow @99dda64 +
# cocoapi @6ac4a93); the TPU equivalent is an exact-version lock of
# the jax/XLA stack AND its transitive closure, generated from the
# environment the test suite and benchmarks actually ran against
# (pip constraints only apply to packages being installed, so entries
# unused by a given image are inert).  tests/test_container.py asserts
# (a) every pip install in the Dockerfiles routes through this file
# and (b) these pins match the live environment — two builds a month
# apart train the identical stack.
#
# Regenerate: python tools/gen_constraints.py > container/constraints.txt
"""


def _norm(name: str) -> str:
    return re.sub(r"[-_.]+", "-", name).lower()


def closure(roots=ROOTS) -> dict[str, tuple[str, str]]:
    seen: dict[str, tuple[str, str]] = {}
    queue = [(n, tuple(extras)) for n, extras in roots]
    while queue:
        # BFS (pop(0)), NOT LIFO: every extras-bearing root must be
        # visited with ITS extras before any transitive dep reaches it
        # extras-less — LIFO visited jax via flax first, so jax[tpu]'s
        # extras-gated deps (libtpu, requests) were only pinned by
        # coincidence via unrelated closure members (ADVICE r4)
        name, extras = queue.pop(0)
        key = _norm(name)
        if key in seen:
            continue
        try:
            dist = distribution(name)
        except PackageNotFoundError:
            continue  # not installed here -> pip resolves it fresh
        seen[key] = (dist.metadata["Name"], dist.version)
        for req in dist.requires or []:
            # extras-gated deps are only resolved when that extra is
            # requested (jax[tpu] → libtpu/requests; plain deps of the
            # closure never request extras of their own deps here)
            if ";" in req:
                marker = req.split(";", 1)[1]
                if "extra" in marker and not any(
                        f'extra == "{e}"' in marker
                        or f"extra == '{e}'" in marker
                        for e in extras):
                    continue
            m = re.match(r"\s*([A-Za-z0-9_.-]+)", req)
            if m:
                queue.append((m.group(1), ()))
    return seen


def main() -> None:
    pins = closure()
    print(HEADER, end="")
    for key in sorted(pins):
        name, ver = pins[key]
        print(f"{name}=={ver}")


if __name__ == "__main__":
    main()
