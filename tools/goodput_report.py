"""Render the cumulative cross-restart goodput ledger of a logdir.

The live meter (eksml_tpu/telemetry/goodput.py) classifies each
segment's wall-clock while it runs and banks snapshots to
``goodput-host<i>.jsonl``; each relaunch starts a new segment.  This
tool merges everything one logdir accumulated — banked snapshots,
flight-recorder events, span traces, checkpoint-commit timestamps —
into ONE whole-run ledger: per-segment bucket tables, the recovered
between-relaunch ``downtime``, the cumulative goodput ratio, and an
**effective-MFU** line that composes the banked predicted step time
(the hermetic roofline, ``artifacts/perf_pred_*.json``) with the
measured goodput: the MFU the run would report if the hardware number
were the predicted one — i.e. how much of the remaining headline gap
is *schedule* (badput) rather than *kernel* speed.

Usage::

    python tools/goodput_report.py <logdir> [--host 0]
                                   [--out artifacts/goodput_rN.json]
                                   [--artifacts artifacts/]

Missing artifacts degrade to notes, never errors — like
run_report.py, this must work on partial evidence (and renders the
same ledger as run_report's "Goodput" section, through the same
builder).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def effective_mfu(goodput_ratio: float,
                  artifacts_dir: str | None = None) -> dict:
    """Compose the banked roofline prediction with measured goodput.

    ideal MFU = predicted-step flops / predicted step time / peak
    flops (the MFU of a run with zero badput on the predicted
    program); effective MFU = ideal × goodput ratio.  Degrades to a
    note when no prediction artifact (or no chip spec) is available.
    """
    if artifacts_dir is None:
        artifacts_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(
                __file__))), "artifacts")
    preds = sorted(glob.glob(os.path.join(artifacts_dir,
                                          "perf_pred_*.json")),
                   key=os.path.getmtime)
    # serving predictions (perf_pred_serve_*, tools/perf_gate.py
    # --serve) price the INFERENCE step — pairing one with a training
    # run's goodput ratio would compose the wrong program's roofline
    preds = [p for p in preds if not os.path.basename(p)
             .startswith("perf_pred_serve_")]
    if not preds:
        return {"note": f"no perf_pred_*.json under {artifacts_dir} "
                        "— run tools/perf_gate.py --update-baseline "
                        "to bank the roofline predictions"}
    path = preds[-1]
    try:
        with open(path) as f:
            rec = json.load(f)
        flops = float(rec["totals"]["flops"])
        pred_ms = float(rec["predicted_step_time_ms"])
        target = rec.get("target", "")
        precision = rec.get("precision", "bfloat16")
        from eksml_tpu.profiling.predict import chip_spec

        spec = chip_spec(target)
        peak = float(spec["peak_flops"].get(precision)
                     or spec["peak_flops"]["bfloat16"])
        ideal = flops / (pred_ms / 1e3) / peak if pred_ms > 0 else 0.0
    except Exception as e:  # noqa: BLE001 — partial evidence is fine
        return {"note": f"could not price {os.path.basename(path)}: "
                        f"{e!r}"}
    return {
        "prediction": os.path.basename(path),
        "target": target,
        "precision": precision,
        "ideal_mfu": round(ideal, 4),
        "goodput_ratio": round(goodput_ratio, 4),
        "effective_mfu": round(ideal * goodput_ratio, 4),
        "note": ("effective = ideal (zero-badput roofline MFU of the "
                 "banked predicted step) x measured goodput ratio — "
                 "smoke-width lowerings overstate ideal_mfu; compare "
                 "the ratio's effect, not absolutes"),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("logdir", help="training run directory")
    p.add_argument("--host", type=int, default=0,
                   help="host whose event stream segments the ledger "
                        "(default 0 = coordinator)")
    p.add_argument("--out", default=None,
                   help="also write the ledger JSON here (atomic)")
    p.add_argument("--artifacts", default=None,
                   help="perf-gate artifact dir for the effective-MFU "
                        "line (default: <repo>/artifacts)")
    args = p.parse_args(argv)

    from eksml_tpu.telemetry.goodput import build_ledger

    ledger = build_ledger(args.logdir, host_id=args.host)
    ledger["effective_mfu"] = effective_mfu(
        ledger.get("goodput_ratio", 0.0), args.artifacts)
    print(json.dumps(ledger, indent=1))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(ledger, indent=1) + "\n")
        os.replace(tmp, args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
