"""Minimal HCL block parser for Terraform contract tests.

python-hcl2 is not in the baked environment and nothing may be
installed (environment rule), so this ~100-line parser extracts the
structure the tests assert on: top-level blocks (``resource``/
``variable``/``output``/``data``/…) with their labels, nested block
types, and attribute assignment source text.  It understands comments
(``#``, ``//``, ``/* */``), quoted strings with ``${}`` interpolation,
and indented heredocs (``<<-EOT``) — the full syntax the repo's
``infra/terraform`` modules use.  It is NOT a general HCL parser and
asserts on unbalanced input rather than guessing.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Tuple


@dataclass
class Block:
    btype: str                     # resource / variable / output / ...
    labels: Tuple[str, ...]        # e.g. ("google_container_cluster", "cluster")
    body: str                      # raw body text (between braces)
    blocks: List["Block"] = field(default_factory=list)   # nested

    @property
    def attrs(self) -> Dict[str, str]:
        """Top-level ``name = <raw text>`` assignments in this body
        (nested block bodies excluded)."""
        depth = 0
        out: Dict[str, str] = {}
        for line in self.body.splitlines():
            stripped = line.strip()
            if depth == 0:
                m = re.match(r"([A-Za-z_][A-Za-z0-9_-]*)\s*=\s*(.+)$",
                             stripped)
                if m and not stripped.startswith("#"):
                    out[m.group(1)] = m.group(2).strip()
            depth += line.count("{") - line.count("}")
            depth = max(depth, 0)
        return out


def _strip_comments(text: str) -> str:
    out: List[str] = []
    i, n = 0, len(text)
    while i < n:
        ch = text[i]
        if ch == '"':                      # quoted string: copy verbatim
            j = i + 1
            while j < n and text[j] != '"':
                j += 2 if text[j] == "\\" else 1
            out.append(text[i:j + 1])
            i = j + 1
        elif text.startswith("<<", i):     # heredoc: copy to terminator
            m = re.match(r"<<-?([A-Za-z_][A-Za-z0-9_]*)", text[i:])
            if not m:
                out.append(ch)
                i += 1
                continue
            tag = m.group(1)
            end = re.search(rf"^\s*{tag}\s*$", text[i:], re.M)
            stop = i + (end.end() if end else len(text) - i)
            out.append(text[i:stop])
            i = stop
        elif ch == "#" or text.startswith("//", i):
            i = text.find("\n", i)
            i = n if i < 0 else i
        elif text.startswith("/*", i):
            j = text.find("*/", i + 2)
            i = n if j < 0 else j + 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def _find_matching_brace(text: str, start: int) -> int:
    """Index of the ``}`` closing the ``{`` at ``start`` (comment-free
    input; strings/heredocs may contain braces via ``${}``)."""
    depth = 0
    i, n = start, len(text)
    while i < n:
        ch = text[i]
        if ch == '"':
            j = i + 1
            while j < n and text[j] != '"':
                if text[j] == "\\":
                    j += 1
                elif text.startswith("${", j):   # interpolation nests
                    d = 1
                    j += 2
                    while j < n and d:
                        d += text[j] == "{"
                        d -= text[j] == "}"
                        j += 1
                    continue
                j += 1
            i = j + 1
            continue
        if text.startswith("<<", i):
            m = re.match(r"<<-?([A-Za-z_][A-Za-z0-9_]*)", text[i:])
            if m:
                end = re.search(rf"^\s*{m.group(1)}\s*$", text[i:], re.M)
                i += end.end() if end else n - i
                continue
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
            if depth == 0:
                return i
        i += 1
    raise ValueError("unbalanced braces in HCL input")


def _parse_blocks(text: str) -> List[Block]:
    blocks: List[Block] = []
    pat = re.compile(
        r'([A-Za-z_][A-Za-z0-9_-]*)((?:\s+"[^"]*")*)\s*\{')
    i = 0
    while True:
        m = pat.search(text, i)
        if not m:
            break
        open_at = m.end() - 1
        close_at = _find_matching_brace(text, open_at)
        labels = tuple(re.findall(r'"([^"]*)"', m.group(2)))
        body = text[open_at + 1:close_at]
        blk = Block(m.group(1), labels, body)
        blk.blocks = _parse_blocks(body)
        blocks.append(blk)
        i = close_at + 1
    return blocks


def parse(path: str) -> List[Block]:
    """Parse one ``.tf`` file into its top-level blocks."""
    return _parse_blocks(_strip_comments(open(path).read()))


def blocks_of(blocks: List[Block], btype: str,
              label0: str | None = None) -> List[Block]:
    return [b for b in blocks
            if b.btype == btype
            and (label0 is None or (b.labels and b.labels[0] == label0))]
