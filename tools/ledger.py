"""Round-over-round perf ledger (VERDICT r2 next #9).

``artifacts/ledger.jsonl`` carries one record per round so progress is
trendable even when a round's live TPU run fails (a wedged tunnel then
still leaves the trajectory on disk).  Append-only; schema pinned by
tests/test_ledger.py.

Usage:
    python tools/ledger.py --round 3 --bench 12.3 --mfu 0.31 \
        --loader-imgs-per-sec 45.0 --convergence-bbox-ap50 0.21 \
        --suite-passed 170 --note "first nonzero TPU bench"
"""

from __future__ import annotations

import argparse
import json
import os
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LEDGER = os.path.join(REPO, "artifacts", "ledger.jsonl")

# Every record carries exactly these keys (None = not measured that
# round); the schema test fails on drift so old rows stay comparable.
FIELDS = ("round", "bench_imgs_per_sec_chip", "mfu",
          "loader_imgs_per_sec", "convergence_bbox_ap50",
          "suite_passed", "note", "noted_at")


def append(round_num: int, bench: float | None = None,
           mfu: float | None = None,
           loader_imgs_per_sec: float | None = None,
           convergence_bbox_ap50: float | None = None,
           suite_passed: int | None = None, note: str = "") -> dict:
    rec = {
        "round": int(round_num),
        "bench_imgs_per_sec_chip": bench,
        "mfu": mfu,
        "loader_imgs_per_sec": loader_imgs_per_sec,
        "convergence_bbox_ap50": convergence_bbox_ap50,
        "suite_passed": suite_passed,
        "note": note,
        "noted_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    os.makedirs(os.path.dirname(LEDGER), exist_ok=True)
    with open(LEDGER, "a") as f:
        f.write(json.dumps(rec) + "\n")
    return rec


def read() -> list:
    if not os.path.exists(LEDGER):
        return []
    with open(LEDGER) as f:
        return [json.loads(line) for line in f if line.strip()]


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--round", type=int, required=True)
    p.add_argument("--bench", type=float, default=None)
    p.add_argument("--mfu", type=float, default=None)
    p.add_argument("--loader-imgs-per-sec", type=float, default=None)
    p.add_argument("--convergence-bbox-ap50", type=float, default=None)
    p.add_argument("--suite-passed", type=int, default=None)
    p.add_argument("--note", default="")
    a = p.parse_args(argv)
    rec = append(a.round, a.bench, a.mfu, a.loader_imgs_per_sec,
                 a.convergence_bbox_ap50, a.suite_passed, a.note)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
