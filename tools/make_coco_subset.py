"""Build the COCO-2017 N-image subset for the run.sh smoke
(BASELINE.json configs[0]: 'COCO-2017 100-image subset, single-process
CPU').  Writes a self-contained dataset directory with the reference's
staged layout (train2017/ val2017/ annotations/ — reference
eks-cluster/stage-data.yaml:30-36 contract), so DATA.BASEDIR can point
straight at it.

Usage::

    python tools/make_coco_subset.py --src /efs/data --dst /efs/data-100 \
        --num-train 100 --num-val 20
"""

from __future__ import annotations

import argparse
import json
import os
import shutil


def subset_split(src: str, dst: str, split: str, n: int) -> None:
    ann_path = os.path.join(src, "annotations", f"instances_{split}.json")
    with open(ann_path) as f:
        data = json.load(f)
    images = sorted(data["images"], key=lambda im: im["id"])[:n]
    keep = {im["id"] for im in images}
    anns = [a for a in data["annotations"] if a["image_id"] in keep]

    os.makedirs(os.path.join(dst, split), exist_ok=True)
    os.makedirs(os.path.join(dst, "annotations"), exist_ok=True)
    for im in images:
        shutil.copy2(os.path.join(src, split, im["file_name"]),
                     os.path.join(dst, split, im["file_name"]))
    ann_path = os.path.join(dst, "annotations",
                            f"instances_{split}.json")
    tmp = ann_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"images": images, "annotations": anns,
                   "categories": data["categories"]}, f)
    os.replace(tmp, ann_path)
    print(f"{split}: {len(images)} images, {len(anns)} annotations")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--src", required=True, help="full COCO basedir")
    p.add_argument("--dst", required=True)
    p.add_argument("--num-train", type=int, default=100)
    p.add_argument("--num-val", type=int, default=20)
    args = p.parse_args()
    subset_split(args.src, args.dst, "train2017", args.num_train)
    subset_split(args.src, args.dst, "val2017", args.num_val)
    pre_src = os.path.join(args.src, "pretrained-models")
    if os.path.isdir(pre_src):
        shutil.copytree(pre_src, os.path.join(args.dst,
                                              "pretrained-models"),
                        dirs_exist_ok=True)
    print(f"subset ready at {args.dst}")


if __name__ == "__main__":
    main()
