"""Generate a LEARNABLE COCO-format dataset of geometric shapes.

The environment has no egress, so real COCO-2017 images cannot be
downloaded (reference eks-cluster/prepare-s3-bucket.sh:21-31 wgets
them).  For convergence evidence (VERDICT r1 item 7) the dataset must
be learnable — class identity must correlate with appearance — which
random-noise synthetic images are not.  This writes JPEGs of solid
geometric shapes on textured backgrounds with exact polygon masks:

  class 1 "box":   axis-aligned warm-colored rectangle
  class 2 "blob":  cool-colored ellipse
  class 3 "wedge": green-ish triangle

A detector that learns anything will drive classification + box losses
down fast and reach nonzero AP within a few hundred steps; one with a
targets/loss/optimizer bug will not.  Layout matches the staged-data
contract (train2017/ val2017/ annotations/, reference
eks-cluster/stage-data.yaml:30-36).

Usage::

    python tools/make_shapes_coco.py --dst /tmp/shapes --num-train 200 \
        --num-val 40 --size 320
"""

from __future__ import annotations

import argparse
import json
import os

import numpy as np

CATEGORIES = [{"id": 1, "name": "box"}, {"id": 2, "name": "blob"},
              {"id": 3, "name": "wedge"}]


def _shape_polygon(cls: int, x: float, y: float, w: float, h: float,
                   rng) -> list:
    """Closed polygon (COCO flat [x0,y0,x1,y1,...]) for one shape."""
    if cls == 1:  # rectangle
        pts = [(x, y), (x + w, y), (x + w, y + h), (x, y + h)]
    elif cls == 2:  # ellipse, 16-gon approximation
        t = np.linspace(0, 2 * np.pi, 16, endpoint=False)
        cx, cy = x + w / 2, y + h / 2
        pts = list(zip(cx + np.cos(t) * w / 2, cy + np.sin(t) * h / 2))
    else:  # triangle
        pts = [(x + w * rng.uniform(0.3, 0.7), y),
               (x + w, y + h), (x, y + h)]
    return [float(v) for p in pts for v in p]


def _rasterize(poly: list, canvas: np.ndarray, color) -> None:
    from eksml_tpu.data.masks import polygon_fill

    h, w = canvas.shape[:2]
    m = polygon_fill(np.asarray(poly, np.float64).reshape(-1, 2), h, w)
    canvas[m.astype(bool)] = color


def _color(cls: int, rng) -> tuple:
    if cls == 1:   # warm
        return (int(rng.randint(180, 256)), int(rng.randint(0, 90)),
                int(rng.randint(0, 90)))
    if cls == 2:   # cool
        return (int(rng.randint(0, 90)), int(rng.randint(0, 90)),
                int(rng.randint(180, 256)))
    return (int(rng.randint(0, 90)), int(rng.randint(180, 256)),
            int(rng.randint(0, 90)))


def make_split(dst: str, split: str, n_img: int, size: int, seed: int,
               id_base: int) -> None:
    from PIL import Image

    rng = np.random.RandomState(seed)
    os.makedirs(os.path.join(dst, split), exist_ok=True)
    images, anns = [], []
    aid = id_base * 10
    for i in range(n_img):
        h = w = size
        # textured background: low-contrast noise around a random gray
        bg = rng.randint(90, 160)
        img = (bg + rng.randint(-25, 25, (h, w, 3))).clip(0, 255) \
            .astype(np.uint8)
        iid = id_base + i
        images.append({"id": iid, "file_name": f"{split}_{i:04d}.jpg",
                       "height": h, "width": w})
        for _ in range(int(rng.randint(1, 4))):
            cls = int(rng.randint(1, 4))
            bw = float(rng.randint(size // 6, size // 2))
            bh = float(rng.randint(size // 6, size // 2))
            x = float(rng.randint(0, int(w - bw)))
            y = float(rng.randint(0, int(h - bh)))
            poly = _shape_polygon(cls, x, y, bw, bh, rng)
            _rasterize(poly, img, _color(cls, rng))
            xs = poly[0::2]
            ys = poly[1::2]
            x0, y0 = min(xs), min(ys)
            bbw, bbh = max(xs) - x0, max(ys) - y0
            anns.append({
                "id": aid, "image_id": iid, "category_id": cls,
                "bbox": [x0, y0, bbw, bbh], "iscrowd": 0,
                "area": bbw * bbh * (0.5 if cls == 3 else
                                     0.78 if cls == 2 else 1.0),
                "segmentation": [poly],
            })
            aid += 1
        Image.fromarray(img).save(
            os.path.join(dst, split, f"{split}_{i:04d}.jpg"), quality=92)
    os.makedirs(os.path.join(dst, "annotations"), exist_ok=True)
    ann_path = os.path.join(dst, "annotations",
                            f"instances_{split}.json")
    tmp = ann_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"images": images, "annotations": anns,
                   "categories": CATEGORIES}, f)
    os.replace(tmp, ann_path)


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--dst", required=True)
    p.add_argument("--num-train", type=int, default=200)
    p.add_argument("--num-val", type=int, default=40)
    p.add_argument("--size", type=int, default=320)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    make_split(args.dst, "train2017", args.num_train, args.size,
               args.seed, 1000)
    make_split(args.dst, "val2017", args.num_val, args.size,
               args.seed + 1, 100000)
    print(f"shapes dataset at {args.dst}: {args.num_train} train / "
          f"{args.num_val} val, {args.size}px")


if __name__ == "__main__":
    main()
