"""Op-level microbench: settle per-op step-time attribution in seconds
of healthy tunnel instead of a full profiled bench run.

Round-5 part-3 motivation: the tiled+stacked NMS and [G, A] anchor
matching were projected (from the banked r5 trace: NMS fusions 82.6
ms/step, matching 10.8 ms/step at 1344/b4) to cut ~90 ms/step, but the
first post-fix headline measured step-time-neutral vs part 1.  This
tool times the production ops — and vendored copies of the PREVIOUS
formulations — directly on whatever backend is up, so one short
healthy window answers which side of the projection was wrong.

Reference cost model being replaced: TF's CUDA NMS kernel + host
matching inside TensorPack (external, /root/reference/container/
Dockerfile:16-19); see ops/nms.py and models/rpn.py for the TPU-first
designs under test.

Usage:
    python tools/op_microbench.py [--iters 20] [--image-size 1344]
        [--batch 4] [--pre-nms 2000] [--ops nms_new,nms_old,...]
        [--out artifacts/op_microbench.json]

Emits one JSON object: {device_kind, params, results: {op: ms}}.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


# ---------------------------------------------------------------------
# Vendored PREVIOUS formulations (pre-24ee096 / pre-2f1ee08), kept
# verbatim-in-spirit so old-vs-new is measured on identical inputs.
# Do not use outside this tool.
# ---------------------------------------------------------------------

def nms_mask_global_fixedpoint(boxes, scores, iou_threshold):
    """The pre-tiling formulation: one synchronous fixed point over the
    full [K, K] suppression matrix (profiled 20.6 ms per FPN level at
    1344 px — the motivation for the tiled rewrite)."""
    from eksml_tpu.ops.boxes import pairwise_iou

    k = boxes.shape[0]
    order = jnp.argsort(-scores)
    sboxes = boxes[order]
    svalid = jnp.isfinite(scores[order])
    iou = pairwise_iou(sboxes, sboxes)
    rank = jnp.arange(k)
    sup = (iou > iou_threshold) & (rank[:, None] < rank[None, :])

    def cond(state):
        keep, prev, it = state
        return (it < k) & jnp.any(keep != prev)

    def body(state):
        keep, _, it = state
        new = svalid & ~jnp.any(sup & keep[:, None], axis=0)
        return new, keep, it + 1

    keep_sorted, _, _ = jax.lax.while_loop(
        cond, body,
        (svalid, jnp.zeros_like(svalid), jnp.zeros((), jnp.int32)))
    return jnp.zeros((k,), dtype=bool).at[order].set(keep_sorted)


def match_anchors_ag(anchors, gt_boxes, gt_valid, pos, neg,
                     gt_crowd=None):
    """The pre-2f1ee08 [A, G] orientation (8 of 128 lanes used;
    profiled fusion.35, 10.8 ms/step) — including BOTH of its full
    [A, G] reductions (the crowd-ignore pass runs even with the
    default all-zero crowd vector, exactly as the production code
    timed as matching_ga still does), so the old-vs-new comparison is
    not biased in old's favor (code review r5c)."""
    from eksml_tpu.ops.boxes import pairwise_iou

    crowd = jnp.zeros_like(gt_valid) if gt_crowd is None else gt_crowd
    target_ok = (gt_valid > 0) & (crowd == 0)
    iou_all = pairwise_iou(anchors, gt_boxes)  # [A, G]
    iou = iou_all * target_ok[None, :].astype(iou_all.dtype)
    best_iou = iou.max(axis=1)
    matched_gt = iou.argmax(axis=1)
    labels = jnp.full(anchors.shape[0], -1, jnp.int32)
    labels = jnp.where(best_iou < neg, 0, labels)
    labels = jnp.where(best_iou >= pos, 1, labels)
    crowd_iou = (iou_all * ((gt_valid > 0) & (crowd > 0))[None, :]
                 ).max(axis=1)
    labels = jnp.where((labels == 0) & (crowd_iou >= neg), -1, labels)
    best_anchor_per_gt = iou.argmax(axis=0)
    force = target_ok & (iou.max(axis=0) > 1e-3)
    labels = labels.at[best_anchor_per_gt].set(
        jnp.where(force, 1, labels[best_anchor_per_gt]))
    has_gt = (target_ok.sum() > 0)
    labels = jnp.where(has_gt, labels,
                       jnp.where(labels == 1, 0, labels))
    return labels, matched_gt


# ---------------------------------------------------------------------
# Realistic inputs: RPN-decoded boxes cluster around objects, which is
# exactly the regime that builds deep suppression chains.
# ---------------------------------------------------------------------

def clustered_boxes(rng, n, img, n_clusters=12):
    centers = rng.rand(n_clusters, 2) * img * 0.8 + img * 0.1
    which = rng.randint(0, n_clusters, size=n)
    ctr = centers[which] + rng.randn(n, 2) * img * 0.02
    size = np.exp(rng.randn(n) * 0.4) * img * 0.08
    ar = np.exp(rng.randn(n) * 0.25)
    w, h = size * ar, size / ar
    x1 = np.clip(ctr[:, 0] - w / 2, 0, img - 2)
    y1 = np.clip(ctr[:, 1] - h / 2, 0, img - 2)
    x2 = np.clip(x1 + w, None, img - 1)
    y2 = np.clip(y1 + h, None, img - 1)
    return np.stack([x1, y1, x2, y2], 1).astype(np.float32)


def timeit(fn, args, iters, warmup=3):
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1000.0


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=20)
    p.add_argument("--image-size", type=int, default=1344)
    p.add_argument("--batch", type=int, default=4)
    p.add_argument("--pre-nms", type=int, default=2000)
    p.add_argument("--nms-thresh", type=float, default=0.7)
    p.add_argument("--ops", default="nms_new,nms_old,nms_new_stacked,"
                   "nms_old_stacked,matching_ga,matching_ag,proposals")
    p.add_argument("--out", default="")
    p.add_argument("--platform", default="")
    p.add_argument("--bank", action="store_true",
                   help="banked-artifact mode (VERDICT r5 next #3): "
                        "timestamp the result and write it to "
                        "<artifacts-dir>/op_microbench_{tpu,cpu}.json "
                        "under the same hardware-evidence gate as "
                        "bench.py, so the old-vs-new attribution "
                        "question is answerable from the ledger")
    p.add_argument("--artifacts-dir",
                   default=os.path.join(os.path.dirname(os.path.dirname(
                       os.path.abspath(__file__))), "artifacts"))
    args = p.parse_args(argv)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from eksml_tpu.models.rpn import generate_proposals, match_anchors
    from eksml_tpu.ops.anchors import generate_fpn_anchors
    from eksml_tpu.ops.nms import nms_mask

    dev = jax.devices()[0]
    rng = np.random.RandomState(0)
    img, K, B = args.image_size, args.pre_nms, args.batch
    L = 5
    thresh = args.nms_thresh

    # [B*L, K] stacked NMS inputs (the production shape after vmap
    # over batch x level), plus a single [K] lane
    stack = np.stack([clustered_boxes(rng, K, img)
                      for _ in range(B * L)])
    sscores = rng.rand(B * L, K).astype(np.float32)
    boxes1, scores1 = jnp.asarray(stack[0]), jnp.asarray(sscores[0])
    boxes_s, scores_s = jnp.asarray(stack), jnp.asarray(sscores)

    strides = (4, 8, 16, 32, 64)
    anchors_np = generate_fpn_anchors(
        (img, img), strides, tuple(s * 8 for s in strides),
        (0.5, 1.0, 2.0))
    A = sum(a.shape[0] for a in anchors_np)
    anchors_all = jnp.asarray(np.concatenate(anchors_np, 0))
    G = 8
    gt = jnp.asarray(np.stack([clustered_boxes(rng, G, img)
                               for _ in range(B)]))
    gt_valid = jnp.asarray((np.arange(G)[None, :]
                            < rng.randint(2, G + 1, (B, 1))
                            ).astype(np.int32))

    # per-level proposal inputs for the end-to-end path
    logits_lv = [jnp.asarray(rng.randn(B, a.shape[0]).astype(np.float32))
                 for a in anchors_np]
    deltas_lv = [jnp.asarray(
        (rng.randn(B, a.shape[0], 4) * 0.1).astype(np.float32))
        for a in anchors_np]
    anchors_lv = [jnp.asarray(a) for a in anchors_np]
    hw = jnp.asarray([[img, img]] * B, jnp.float32)

    ops = {}
    ops["nms_new"] = (jax.jit(lambda b, s: nms_mask(b, s, thresh)),
                      (boxes1, scores1))
    ops["nms_old"] = (jax.jit(lambda b, s: nms_mask_global_fixedpoint(
        b, s, thresh)), (boxes1, scores1))
    ops["nms_new_stacked"] = (jax.jit(jax.vmap(
        lambda b, s: nms_mask(b, s, thresh))), (boxes_s, scores_s))
    ops["nms_old_stacked"] = (jax.jit(jax.vmap(
        lambda b, s: nms_mask_global_fixedpoint(b, s, thresh))),
        (boxes_s, scores_s))
    ops["matching_ga"] = (jax.jit(jax.vmap(
        lambda g, v: match_anchors(anchors_all, g, v, 0.7, 0.3))),
        (gt, gt_valid))
    ops["matching_ag"] = (jax.jit(jax.vmap(
        lambda g, v: match_anchors_ag(anchors_all, g, v, 0.7, 0.3))),
        (gt, gt_valid))
    ops["proposals"] = (jax.jit(jax.vmap(
        lambda lg, dl, h: generate_proposals(
            lg, dl, anchors_lv, h, K, 512, thresh),
        in_axes=(0, 0, 0))),
        (logits_lv, deltas_lv, hw))

    wanted = [w.strip() for w in args.ops.split(",") if w.strip()]
    bad = [w for w in wanted if w not in ops]
    if bad:
        raise SystemExit(f"unknown ops {bad}; known: {sorted(ops)}")

    results = {}
    for name in wanted:
        fn, a = ops[name]
        try:
            results[name] = round(timeit(fn, a, args.iters), 3)
        except Exception as e:  # noqa: BLE001 — record, keep measuring
            results[name] = f"ERROR: {type(e).__name__}: {e}"[:300]
        print(f"{name}: {results[name]}", file=sys.stderr)

    out = {
        "device_kind": dev.device_kind,
        "params": {"image_size": img, "batch": B, "pre_nms": K,
                   "levels": L, "anchors_total": int(A),
                   "iters": args.iters,
                   "nms_tile": os.environ.get("EKSML_NMS_TILE", "256")},
        "results": results,
        "unit": "ms_per_call",
    }
    # the question this tool exists to answer, precomputed: how much
    # did each rewrite actually move on identical inputs (negative =
    # the new formulation is faster)
    deltas = {}
    for new, old in (("nms_new", "nms_old"),
                     ("nms_new_stacked", "nms_old_stacked"),
                     ("matching_ga", "matching_ag")):
        a, b = results.get(new), results.get(old)
        if isinstance(a, (int, float)) and isinstance(b, (int, float)):
            deltas[f"{new}_minus_{old}"] = round(a - b, 3)
    if deltas:
        out["new_minus_old_ms"] = deltas
    line = json.dumps(out)
    print(line)
    if args.out:
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            f.write(line + "\n")
        os.replace(tmp, args.out)
    if args.bank:
        # same stamp + hardware gate as bench.py's banked artifacts —
        # a CPU run self-labels instead of masquerading as the TPU
        # answer the round is waiting on
        from bench import is_hardware, utcnow

        out["banked_at"] = utcnow()
        name = ("op_microbench_tpu.json" if is_hardware(out)
                else "op_microbench_cpu.json")
        path = os.path.join(args.artifacts_dir, name)
        os.makedirs(args.artifacts_dir, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(out) + "\n")
        os.replace(tmp, path)
        print(f"op_microbench: banked to {path}", file=sys.stderr)


if __name__ == "__main__":
    main()
