"""Hermetic predicted-step-time perf gate — no TPU, no tunnel.

Every banked bench round r01–r05 reports 0.0 img/s (tunnel/backend
failures), so ``tools/bench_gate.py`` has had nothing fresh to gate on
for five rounds.  This tool gates what CAN be produced on every CI
box: AOT-lower the real train step for a named TPU target under
``JAX_PLATFORMS=cpu``, price the compiled HLO with the roofline model
(``eksml_tpu/profiling/predict.py``), and compare the predicted step
time — per component and total — against the banked prediction
baseline.

- **bank**: ``artifacts/perf_pred_<rung>_<strategy>_<precision>.json``
  — one baseline per rung geometry × sharding strategy × precision.
  ``--update-baseline`` (re)banks fresh predictions (run it once when
  a prediction-moving change is INTENDED, and commit the diff).
- **gate**: a fresh prediction regressing more than
  ``--max-regress-pct`` vs its banked baseline FAILs with a
  component-attributed message ("backbone-bwd predicted +34%"), never
  a bare number.  A big component regression hidden by an unrelated
  win fails too (compare_predictions).
- **calibration**: every run reports the model's honesty — one scale
  factor per rung fitted against the banked r5 hardware artifacts
  (``artifacts/roi_ab_r5.json``, ``bench_rung_1344_b4.json``), with
  the cross-rung spread printed as ``model_error_pct``.  When new
  hardware numbers land (bench.py now emits predicted next to
  measured), the fit tightens automatically.

The model is lowered at the SMOKE channel widths (config
SMOKE_OVERRIDES) so a CI box compiles each geometry in tens of
seconds; the canvas/batch — what decides program structure and
relative cost — are the real rung geometry.  Absolute milliseconds are
therefore model-scale, not hardware-scale; the gate only ever compares
prediction RATIOS, and the calibration section quantifies how far
ratios can be trusted.

Usage::

    # CI gate (CPU-only, bounded): 2 geometries x 4 strategies
    # (replicated, fsdp, tensor, 2d — the sharded lowerings price
    # their fsdp-/model-axis collectives)
    python tools/perf_gate.py

    # accept an intended prediction change / first-time banking
    python tools/perf_gate.py --update-baseline

    # calibration report only (no lowering — pure artifact math)
    python tools/perf_gate.py --calibrate-only
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from eksml_tpu.fsio import atomic_write_json, atomic_write_text  # noqa: E402

# Rung geometries the predictor lowers (canvas × batch, plus the knobs
# a rung pre-plans — mirrors bench.py RUNGS where the names overlap so
# a measured rung pairs with its prediction by name).
PRED_RUNGS: Dict[str, Dict[str, Any]] = {
    "128_b1": {"image_size": 128, "batch_size": 1},
    "256_b1": {"image_size": 256, "batch_size": 1},
    "512_b1": {"image_size": 512, "batch_size": 1},
    "512_b4": {"image_size": 512, "batch_size": 4},
    "832x1344_b4": {"pad_hw": (832, 1344), "batch_size": 4},
    "1344_b4": {"image_size": 1344, "batch_size": 4},
    "1344_b8_remat": {"image_size": 1344, "batch_size": 8,
                      "remat": True, "param_dtype": "bfloat16"},
    # multi-slice rungs: a slice is internally fsdp x model (the 2D
    # layout), slices exchange only gradients over DCN.  Lowered with
    # the hierarchical exchange and priced BOTH ways from the same
    # HLO — the rung FAILs unless hierarchical is strictly faster
    # than the flat DCN ring (the win this gate exists to gate).
    # "strategies" restricts the plan: a slice axis only means
    # anything composed with a sharded in-slice layout.
    "128_b1_s2": {"image_size": 128, "batch_size": 1,
                  "num_slices": 2, "strategies": ("2d",)},
    "128_b1_s4": {"image_size": 128, "batch_size": 1,
                  "num_slices": 4, "strategies": ("2d",)},
}

#: the CI default: two cheap geometries × every executable strategy
#: plus the two multi-slice rungs (2d-only) — ~10 tiny-model
#: compiles, bounded minutes on one CPU core (the tensor/2d rungs
#: price the model-axis collectives hermetically; the _s2/_s4 rungs
#: price the cross-slice DCN exchange hierarchical-vs-flat)
DEFAULT_RUNGS = "128_b1,256_b1,128_b1_s2,128_b1_s4"
DEFAULT_STRATEGIES = "replicated,fsdp,tensor,2d"

# Serving (bucket, batch) rungs priced by --serve: the PREDICT step
# the serving engine's AOT cache warms (eksml_tpu/serve/engine.py),
# lowered at SMOKE widths like the training rungs — CI gets a
# per-bucket predicted-latency verdict with no hardware and no
# tunnel.  Names mirror the serve bucket schedule at smoke geometry.
SERVE_PRED_RUNGS: Dict[str, Dict[str, Any]] = {
    "serve_128x128_b1": {"pad_hw": (128, 128), "batch_size": 1},
    "serve_128x128_b4": {"pad_hw": (128, 128), "batch_size": 4},
}

DEFAULT_SERVE_RUNGS = "serve_128x128_b1,serve_128x128_b4"


def pred_key(rung: str, strategy: str, precision: str) -> str:
    return f"{rung}_{strategy}_{precision}"


def baseline_path(bank_dir: str, key: str) -> str:
    return os.path.join(bank_dir, f"perf_pred_{key}.json")


def _utcnow() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def _load_json(path: str) -> Optional[Dict]:
    # ONE loader with the calibration pairing (predict.load_json)
    from eksml_tpu.profiling.predict import load_json

    return load_json(path)


def _rung_config(rung: str, precision: str, config_overrides):
    """Global config → the rung's geometry at SMOKE widths, finalized.

    Mutates the process-global config (the CLI owns the process); tests
    go through the fresh_config fixture instead and call
    predict.lower_train_step directly."""
    from eksml_tpu.config import (SMOKE_OVERRIDES, config,
                                  finalize_configs)

    spec = PRED_RUNGS[rung]
    size = (max(spec["pad_hw"]) if spec.get("pad_hw")
            else spec["image_size"])
    config.freeze(False)
    config.update_args(SMOKE_OVERRIDES)
    config.TRAIN.PRECISION = precision
    config.TRAIN.REMAT = bool(spec.get("remat", False))
    config.TRAIN.PARAM_DTYPE = spec.get("param_dtype", "float32")
    config.TRAIN.BATCH_SIZE_PER_CHIP = spec["batch_size"]
    config.PREPROC.MAX_SIZE = size
    config.PREPROC.TRAIN_SHORT_EDGE_SIZE = (size, size)
    config.update_args(config_overrides or [])
    return finalize_configs(is_training=True)


def axis_widths(mesh_shape: Dict[str, Any]) -> Dict[str, int]:
    """Resolved (fsdp, model) widths of a lowered rung's mesh — the
    verdict-row field that keeps a 2d rung from being confused with
    its 1D siblings in the bank (same rung name, same strategy
    string, different shard widths).  A mesh with a ``slice`` axis
    adds a ``slices`` column; single-slice rows keep the historical
    two-key shape (banked artifacts and their consumers pin it)."""
    widths = {"fsdp": int((mesh_shape or {}).get("fsdp", 1)),
              "model": int((mesh_shape or {}).get("model", 1))}
    slices = int((mesh_shape or {}).get("slice", 1))
    if slices > 1:
        widths["slices"] = slices
    return widths


def row_axis_widths(rec: Dict[str, Any]) -> Optional[Dict[str, int]]:
    """Widths for a verdict row, derived from the ``mesh_shape`` the
    record already banks (no second copy to drift) — None for serve
    predict records (no training mesh) and pre-mesh_shape banks."""
    if rec.get("kind") == "predict" or "mesh_shape" not in rec:
        return None
    return axis_widths(rec["mesh_shape"])


def predict_rung(rung: str, strategy: str, precision: str,
                 target: str, fsdp_axis: int = 2, model_axis: int = 2,
                 config_overrides=None) -> Dict[str, Any]:
    """Lower one rung × strategy and price it for ``target`` —
    the fresh-prediction record the gate compares and banks."""
    from eksml_tpu.profiling import predict as P

    spec = PRED_RUNGS[rung]
    cfg = _rung_config(rung, precision, config_overrides)
    # cfg wins over the flag: a --config TRAIN.PRECISION override
    # changed the lowered program, and pricing/keying it as the flag
    # precision would overwrite the wrong baseline (the bench.py
    # re-derivation rule)
    precision = str(cfg.TRAIN.PRECISION)
    num_slices = int(spec.get("num_slices", 1))
    exchange = "hierarchical" if num_slices > 1 else "flat"
    t0 = time.time()
    hlo, meta = P.lower_train_step(
        cfg, batch_size=spec["batch_size"],
        image_size=spec.get("image_size"),
        pad_hw=spec.get("pad_hw"), strategy=strategy,
        fsdp_axis=fsdp_axis, model_axis=model_axis,
        num_slices=num_slices, exchange=exchange)
    slice_devices = (meta["slice_devices"] if num_slices > 1
                     else None)
    pred = P.predict_from_hlo(hlo, target=target, precision=precision,
                              comm_sizes=meta["comm_sizes"],
                              slice_devices=slice_devices,
                              exchange=exchange,
                              input_groups=meta["input_groups"])
    rec = dict(pred)
    rec.update({
        "rung": rung,
        "key": pred_key(rung, strategy, precision),
        "strategy": strategy,
        "geometry": {k: meta[k] for k in ("batch_size", "image_size",
                                          "remat", "param_dtype")},
        "mesh_shape": meta["mesh_shape"],
        # the widths disclaimer: absolute ms are model-scale (smoke
        # channel widths unless the caller overrode them) — gate on
        # ratios, read the calibration section for trust bounds
        "model_widths": "smoke",
        "lower_seconds": round(time.time() - t0, 1),
        "banked_at": _utcnow(),
    })
    if num_slices > 1:
        # price the SAME compiled program as one flat ring at the
        # slowest link — the counterfactual the hierarchical exchange
        # is gated against (it must be strictly faster, gate_one)
        flat = P.predict_from_hlo(
            hlo, target=target, precision=precision,
            comm_sizes=meta["comm_sizes"],
            slice_devices=slice_devices, exchange="flat")
        rec.update({
            "num_slices": num_slices,
            "slice_devices": meta["slice_devices"],
            "exchange": exchange,
            "flat_predicted_step_time_ms":
                flat["predicted_step_time_ms"],
        })
    return rec


def _serve_rung_config(rung: str, precision: str, config_overrides):
    """Global config → the serve rung's inference geometry at SMOKE
    widths, finalized for inference (``is_training=False`` — the
    server's own finalize call)."""
    from eksml_tpu.config import (SMOKE_OVERRIDES, config,
                                  finalize_configs)

    spec = SERVE_PRED_RUNGS[rung]
    size = max(spec["pad_hw"])
    config.freeze(False)
    config.update_args(SMOKE_OVERRIDES)
    config.TRAIN.PRECISION = precision
    config.PREPROC.MAX_SIZE = size
    config.PREPROC.TEST_SHORT_EDGE_SIZE = min(spec["pad_hw"])
    config.TEST.EVAL_BATCH_SIZE = spec["batch_size"]
    config.update_args(config_overrides or [])
    return finalize_configs(is_training=False)


def predict_serve_rung(rung: str, precision: str, target: str,
                       config_overrides=None) -> Dict[str, Any]:
    """Lower one serving (bucket, batch) rung's PREDICT step and
    price it for ``target`` — the per-bucket predicted-latency record
    the --serve gate compares and banks."""
    from eksml_tpu.profiling import predict as P

    spec = SERVE_PRED_RUNGS[rung]
    cfg = _serve_rung_config(rung, precision, config_overrides)
    # cfg wins over the flag (the bench.py re-derivation rule): a
    # --config TRAIN.PRECISION override changed the lowered program
    precision = str(cfg.TRAIN.PRECISION)
    t0 = time.time()
    hlo, meta = P.lower_predict_step(
        cfg, batch_size=spec["batch_size"], pad_hw=spec["pad_hw"])
    pred = P.predict_from_hlo(hlo, target=target, precision=precision,
                              comm_sizes=meta["comm_sizes"],
                              input_groups=meta["input_groups"])
    rec = dict(pred)
    rec.update({
        "rung": rung,
        "key": f"{rung}_{precision}",
        "kind": "predict",
        "geometry": {k: meta[k] for k in ("batch_size", "pad_hw",
                                          "device_normalize")},
        # the serving SLO framing of the same number: predicted
        # device time for ONE dispatched (bucket, batch) executable
        "predicted_latency_ms": pred["predicted_step_time_ms"],
        "predicted_latency_per_image_ms": round(
            pred["predicted_step_time_ms"] / spec["batch_size"], 4),
        "model_widths": "smoke",
        "lower_seconds": round(time.time() - t0, 1),
        "banked_at": _utcnow(),
    })
    return rec


def hbm_columns(rec: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The memory verdict columns a prediction record contributes to
    its gate row — None for pre-observatory records (no ``hbm``)."""
    hbm = rec.get("hbm") or {}
    if not hbm.get("peak_hbm_bytes"):
        return None
    cap = hbm.get("capacity") or {}
    return {
        "peak_hbm_bytes": hbm["peak_hbm_bytes"],
        "headroom_bytes": cap.get("headroom_bytes"),
        "utilization_pct": cap.get("utilization_pct"),
        "fits": bool(cap.get("fits", True)),
    }


def hbm_regression_error(fresh: Dict, base: Dict,
                         max_regress_pct: float
                         ) -> Optional[str]:
    """Peak-HBM regression beyond the bound → the FAIL message naming
    the component whose live-at-peak bytes grew most; None when in
    bounds or either record predates the observatory."""
    fh = fresh.get("hbm") or {}
    bh = base.get("hbm") or {}
    fp, bp = fh.get("peak_hbm_bytes"), bh.get("peak_hbm_bytes")
    if not fp or not bp:
        return None
    pct = 100.0 * (float(fp) / float(bp) - 1.0)
    if pct <= max_regress_pct:
        return None
    fc = fh.get("live_at_peak_by_component") or {}
    bc = bh.get("live_at_peak_by_component") or {}
    worst = max(set(fc) | set(bc) or {"other"},
                key=lambda k: fc.get(k, 0) - bc.get(k, 0))
    return (f"predicted peak HBM regressed +{pct:.1f}% "
            f"({bp} -> {fp} bytes, bound {max_regress_pct}%); worst "
            f"component {worst}: live-at-peak {bc.get(worst, 0)} -> "
            f"{fc.get(worst, 0)} bytes")


def hbm_cross_rows(fresh_records: List[Dict[str, Any]]
                   ) -> List[Dict[str, Any]]:
    """The sharding cross-gate: at the same rung geometry, the 2d
    lowering's predicted peak HBM must be STRICTLY below replicated's
    (params+optimizer+grads divide over fsdp x model while per-device
    activations match — PR 15's measured 19.2% storage claim as a
    hermetic invariant).  One verdict row per rung where this run
    lowered both strategies."""
    by_rung: Dict[str, Dict[str, Dict]] = {}
    for rec in fresh_records:
        rung, strat = rec.get("rung"), rec.get("strategy")
        if rung and strat in ("replicated", "2d"):
            by_rung.setdefault(rung, {})[strat] = rec
    rows: List[Dict[str, Any]] = []
    for rung in sorted(by_rung):
        pair = by_rung[rung]
        if "replicated" not in pair or "2d" not in pair:
            continue
        rp = ((pair["replicated"].get("hbm") or {})
              .get("peak_hbm_bytes"))
        dp = ((pair["2d"].get("hbm") or {}).get("peak_hbm_bytes"))
        if not rp or not dp:
            continue
        row: Dict[str, Any] = {
            "key": f"{rung}_hbm_cross_strategy",
            "check": "2d predicted peak strictly below replicated",
            "replicated_peak_hbm_bytes": rp,
            "2d_peak_hbm_bytes": dp,
            "peak_ratio_pct": round(100.0 * dp / rp, 2),
            "gate": "PASS" if dp < rp else "FAIL",
        }
        if row["gate"] == "FAIL":
            row["error"] = (
                f"at rung {rung} the 2d lowering's predicted peak HBM "
                f"({dp} bytes) is not strictly below replicated's "
                f"({rp} bytes) — sharding stopped paying for its "
                f"per-device memory plan")
        rows.append(row)
    return rows


def gate_one(fresh: Dict, bank_dir: str, max_regress_pct: float,
             allow_missing_baseline: bool) -> Dict[str, Any]:
    """Fresh prediction vs its banked baseline → one result row."""
    from eksml_tpu.profiling.memory import top_components
    from eksml_tpu.profiling.predict import compare_predictions

    path = baseline_path(bank_dir, fresh["key"])
    base = _load_json(path)
    row: Dict[str, Any] = {
        "key": fresh["key"],
        "predicted_step_time_ms": fresh["predicted_step_time_ms"],
        "sections_ms": fresh["sections_ms"],
        "baseline_path": os.path.relpath(path, REPO),
    }
    if fresh.get("comms_ms") is not None:
        # the per-link communication columns (ISSUE 19): predicted
        # ici/dcn/exposed ms ride every verdict row so a comms move
        # is visible at the link level, not just inside the total
        row["comms_ms"] = fresh["comms_ms"]
    widths = row_axis_widths(fresh)
    if widths is not None:
        # resolved shard widths ride every verdict row: a 2d rung and
        # its 1D siblings share rung names, and the bank must never
        # let one masquerade as the other
        row["axis_widths"] = widths
    flat_ms = fresh.get("flat_predicted_step_time_ms")
    if flat_ms is not None:
        # the multi-slice rung's reason to exist: under the banked
        # DCN calibration the hierarchical exchange must be strictly
        # faster than pricing the same program as one flat ring at
        # the slowest link — equal-or-slower means the three-phase
        # schedule is not paying for itself
        row["flat_predicted_step_time_ms"] = flat_ms
        if fresh["predicted_step_time_ms"] >= flat_ms:
            row["gate"] = "FAIL"
            row["error"] = (
                f"hierarchical exchange predicted "
                f"{fresh['predicted_step_time_ms']}ms is not "
                f"strictly faster than the flat DCN ring "
                f"({flat_ms}ms) at num_slices="
                f"{fresh.get('num_slices')} — the exchange pricing "
                f"or the staged collectives regressed")
            return row
    mem = hbm_columns(fresh)
    if mem is not None:
        # the memory verdict columns (ISSUE 20) ride every row; the
        # capacity half needs no baseline — a rung that does not fit
        # the chip FAILs naming its top live-at-peak components
        row["hbm"] = mem
        if not mem["fits"]:
            cap = (fresh["hbm"].get("capacity") or {})
            row["gate"] = "FAIL"
            row["error"] = row["hbm"]["error"] = (
                f"predicted peak HBM {mem['peak_hbm_bytes']} bytes "
                f"exceeds {fresh.get('target', '?')} capacity "
                f"{cap.get('hbm_bytes')} bytes — top live-at-peak: "
                f"{top_components(fresh['hbm'])}")
            return row
    if base is not None:
        base_widths = row_axis_widths(base)
        if (widths is not None and base_widths is not None
                and widths != base_widths):
            # pred_key excludes the widths, so a lowering at other
            # --fsdp-axis/--model-axis values lands under the SAME
            # baseline file — comparing their times would be a bogus
            # verdict about nothing; fail naming both layouts
            row["gate"] = "FAIL"
            row["baseline_axis_widths"] = base_widths
            row["error"] = (
                f"axis widths mismatch: fresh lowering is "
                f"fsdp={widths['fsdp']} x model={widths['model']} but "
                f"the banked baseline is fsdp={base_widths['fsdp']} x "
                f"model={base_widths['model']} — pass the matching "
                f"--fsdp-axis/--model-axis, or re-bank with "
                f"--update-baseline if the new widths are intended")
            return row
    if base is None:
        row["gate"] = "PASS" if allow_missing_baseline else "FAIL"
        row["error"] = (
            f"no banked baseline at {path} — run tools/perf_gate.py "
            "--update-baseline once and commit the artifact"
        ) if not allow_missing_baseline else None
        row["note"] = "missing baseline"
        return row
    ok, verdict = compare_predictions(fresh, base,
                                      max_regress_pct=max_regress_pct)
    row["gate"] = "PASS" if ok else "FAIL"
    row["verdict"] = verdict
    if not ok:
        row["error"] = verdict.get("error")
    if mem is not None and (base.get("hbm") or {}).get(
            "peak_hbm_bytes"):
        # the regression half of the memory verdict: baseline peak +
        # delta always ride the columns; beyond the bound the row
        # FAILs naming the component whose live-at-peak bytes grew
        # most (time error — the pinned message — stays primary when
        # both regress)
        base_peak = base["hbm"]["peak_hbm_bytes"]
        row["hbm"]["baseline_peak_hbm_bytes"] = base_peak
        row["hbm"]["peak_regress_pct"] = round(
            100.0 * (float(mem["peak_hbm_bytes"]) / float(base_peak)
                     - 1.0), 2)
        mem_err = hbm_regression_error(fresh, base, max_regress_pct)
        if mem_err:
            row["gate"] = "FAIL"
            row["hbm"]["error"] = mem_err
            if not row.get("error"):
                row["error"] = mem_err
    return row


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--rungs", default=DEFAULT_RUNGS,
                   help=f"comma list of {sorted(PRED_RUNGS)} "
                        f"[%(default)s]")
    p.add_argument("--strategies", default=DEFAULT_STRATEGIES,
                   help="comma list of sharding strategies to lower "
                        "(replicated, fsdp, tensor, 2d) "
                        "[%(default)s]")
    p.add_argument("--target", default="v5e",
                   help="chip spec the roofline prices for "
                        "(predict.CHIP_SPECS) [%(default)s]")
    p.add_argument("--precision", default="bfloat16",
                   choices=["bfloat16", "float32"])
    p.add_argument("--fsdp-axis", type=int, default=2,
                   help="fsdp axis size for the fsdp/2d lowerings "
                        "(host-platform virtual devices) [%(default)s]")
    p.add_argument("--model-axis", type=int, default=2,
                   help="model axis size for the tensor/2d lowerings "
                        "(host-platform virtual devices) [%(default)s]")
    p.add_argument("--bank-dir",
                   default=os.path.join(REPO, "artifacts"),
                   help="where perf_pred_*.json baselines live")
    p.add_argument("--fresh-dir", default=None,
                   help="also write fresh predictions here (e.g. for "
                        "bench_gate --predicted); default: only the "
                        "verdict JSON carries them")
    p.add_argument("--max-regress-pct", type=float, default=10.0)
    p.add_argument("--update-baseline", action="store_true",
                   help="(re)bank fresh predictions as the baseline "
                        "instead of gating against it")
    p.add_argument("--allow-missing-baseline", action="store_true")
    p.add_argument("--calibrate-only", action="store_true",
                   help="skip lowering; print the calibration report "
                        "from banked artifacts (pure JSON math)")
    p.add_argument("--serve", action="store_true",
                   help="gate the SERVING predict step instead of the "
                        "train step: lower each (bucket, batch) rung "
                        "of the serve engine's AOT cache and price "
                        "its latency (perf_pred_serve_* baselines)")
    p.add_argument("--serve-rungs", default=DEFAULT_SERVE_RUNGS,
                   help=f"comma list of {sorted(SERVE_PRED_RUNGS)} "
                        f"[%(default)s]")
    p.add_argument("--out", default=None,
                   help="write the verdict JSON here too")
    p.add_argument("--config", nargs="*", default=[],
                   help="KEY=VALUE config overrides applied on top of "
                        "the rung geometry (synthetic-regression "
                        "probes, width experiments)")
    args = p.parse_args(argv)

    # hermetic by construction: this tool only compiles — it must
    # never touch a TPU backend or the tunnel, even on a TPU host.
    # Env first (the fsdp lowering needs >=2 host-platform devices and
    # XLA reads the flag at backend init), then the config pin for
    # processes whose site hook already imported jax.  --calibrate-only
    # never compiles, so it skips the jax import entirely (it is pure
    # JSON math and tpu_harvest runs it on the TPU host post-window).
    if not args.calibrate_only:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            # the 2d lowering shards over fsdp x model jointly — the
            # host platform must carry the axis PRODUCT, times the
            # widest slice count any requested rung lowers at
            max_slices = max(
                [1] + [int(PRED_RUNGS[r.strip()].get("num_slices", 1))
                       for r in args.rungs.split(",")
                       if r.strip() in PRED_RUNGS])
            n_virtual = max(2, args.fsdp_axis, args.model_axis,
                            args.fsdp_axis * args.model_axis
                            * max_slices)
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count="
                        f"{n_virtual}").strip()
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001 — backend already up
            pass

    from eksml_tpu.profiling.predict import calibrate, calibration_points

    verdict: Dict[str, Any] = {
        "target": args.target,
        "precision": args.precision,
        "max_regress_pct": args.max_regress_pct,
        "model_widths": "smoke",
        "results": [],
    }

    ok = True
    run_precision = args.precision
    if not args.calibrate_only:
        if args.serve:
            verdict["mode"] = "serve"
            rungs = [r.strip() for r in args.serve_rungs.split(",")
                     if r.strip()]
            bad = [r for r in rungs if r not in SERVE_PRED_RUNGS]
            if bad:
                p.error(f"unknown serve rung(s) {bad}; known: "
                        f"{sorted(SERVE_PRED_RUNGS)}")
            # one (rung,) pseudo-strategy axis: the predict program
            # has no sharding strategy — serving is per-replica
            plan = [(rung, None) for rung in rungs]
        else:
            rungs = [r.strip() for r in args.rungs.split(",")
                     if r.strip()]
            strategies = [s.strip() for s in args.strategies.split(",")
                          if s.strip()]
            bad = [r for r in rungs if r not in PRED_RUNGS]
            if bad:
                p.error(f"unknown rung(s) {bad}; known: "
                        f"{sorted(PRED_RUNGS)}")
            # a rung may restrict its strategy axis (the multi-slice
            # rungs only mean anything over a sharded in-slice
            # layout) — absent the key, every requested strategy runs
            plan = [(rung, strategy) for rung in rungs
                    for strategy in strategies
                    if strategy in PRED_RUNGS[rung].get("strategies",
                                                        strategies)]
        fresh_records: List[Dict[str, Any]] = []
        for rung, strategy in plan:
            print(f"perf_gate: lowering {rung}"
                  + (f" x {strategy}" if strategy else " (serve)")
                  + " ...", file=sys.stderr)
            if strategy is None:
                fresh = predict_serve_rung(
                    rung, args.precision, args.target,
                    config_overrides=args.config)
            else:
                fresh = predict_rung(
                    rung, strategy, args.precision, args.target,
                    fsdp_axis=args.fsdp_axis,
                    model_axis=args.model_axis,
                    config_overrides=args.config)
            # the record's key, NOT pred_key(..., args.precision):
            # a --config TRAIN.PRECISION override re-keyed the
            # record, and writing it under the flag's key would
            # overwrite the wrong baseline file
            key = fresh["key"]
            fresh_records.append(fresh)
            run_precision = fresh["precision"]
            print(f"perf_gate: {key}: predicted "
                  f"{fresh['predicted_step_time_ms']}ms "
                  f"(lowered in {fresh['lower_seconds']}s)",
                  file=sys.stderr)
            if args.fresh_dir:
                os.makedirs(args.fresh_dir, exist_ok=True)
                # atomic: bench_gate --predicted may poll this
                # dir while we lower the next rung
                atomic_write_json(os.path.join(
                    args.fresh_dir, f"perf_pred_{key}.json"),
                    fresh)
            if args.update_baseline:
                os.makedirs(args.bank_dir, exist_ok=True)
                path = baseline_path(args.bank_dir, key)
                atomic_write_json(path, fresh)
                banked_row = {
                    "key": key, "gate": "BANKED",
                    "predicted_step_time_ms":
                        fresh["predicted_step_time_ms"],
                    "sections_ms": fresh["sections_ms"],
                    "baseline_path": os.path.relpath(path, REPO)}
                if fresh.get("comms_ms") is not None:
                    banked_row["comms_ms"] = fresh["comms_ms"]
                widths = row_axis_widths(fresh)
                if widths is not None:
                    banked_row["axis_widths"] = widths
                if "flat_predicted_step_time_ms" in fresh:
                    banked_row["flat_predicted_step_time_ms"] = (
                        fresh["flat_predicted_step_time_ms"])
                mem = hbm_columns(fresh)
                if mem is not None:
                    banked_row["hbm"] = mem
                verdict["results"].append(banked_row)
            else:
                row = gate_one(fresh, args.bank_dir,
                               args.max_regress_pct,
                               args.allow_missing_baseline)
                ok = ok and row["gate"] != "FAIL"
                verdict["results"].append(row)
        if not args.serve:
            # the sharding memory cross-gate (2d strictly below
            # replicated at the same rung) runs in BOTH modes —
            # --update-baseline must never bank a violating pair
            for row in hbm_cross_rows(fresh_records):
                ok = ok and row["gate"] != "FAIL"
                verdict["results"].append(row)

    # the honesty check rides every run: how far can the model's
    # ratios be trusted, per the banked hardware evidence.
    # run_precision, not the flag: a --config TRAIN.PRECISION
    # override re-keyed the records, and the header/calibration must
    # describe the precision that was actually lowered
    verdict["precision"] = run_precision
    verdict["calibration"] = calibrate(
        calibration_points(args.bank_dir, precision=run_precision))

    verdict["gate"] = "PASS" if ok else "FAIL"
    payload = json.dumps(verdict, indent=1)
    print(payload)
    if args.out:
        atomic_write_text(args.out, payload)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
