#!/usr/bin/env python3
"""Publish ``preemption_forecast`` into the operator's capacity file.

The autoscale policy (eksml_tpu/resilience/autoscale.py) holds
scale-ups when ``preemption_forecast >= FORECAST_HOLD`` — but until
now nothing populated that field: FileCapacityProvider read whatever
a human (or a chaos rung) wrote.  This tool closes the loop with the
same pluggable-provider pattern as the operator's capacity side:

  forecast = chips_on_termination_notice / max(total_chips, 1)

clamped to [0, 1].  Two notice providers:

* ``FileNoticeProvider`` — a JSON stub for local runs and chaos
  rungs: ``{"total_chips": 16, "notices": [{"node": "n1",
  "chips": 4}, ...]}``.  Torn or absent file reads as "no signal"
  (None), never as forecast 0 — a crashed notice feed must not
  clear a standing hold.
* ``KubectlNoticeProvider`` — the in-cluster signal: sums the TPU
  allocatable of Ready nodes carrying a termination taint (GKE
  spot/autoscaler keys by default) over the allocatable of all Ready
  nodes.

The write side is a read-modify-write of the operator's capacity
file preserving every other field (``available_chips`` belongs to
whoever feeds capacity), via tmp + ``os.replace`` so FileCapacity-
Provider on the operator side never sees a torn document.  A missing
or torn capacity file is skipped — this tool annotates the capacity
feed, it does not own the file.

Stdlib-only on purpose: it runs as a cluster sidecar/cron where the
eksml_tpu package may not be installed.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional


class NoticeSignal:
    """Chips under termination notice out of the fleet total."""

    def __init__(self, chips_on_notice: int, total_chips: int):
        self.chips_on_notice = max(0, int(chips_on_notice))
        self.total_chips = max(0, int(total_chips))

    def forecast(self) -> float:
        return min(1.0, self.chips_on_notice / max(self.total_chips, 1))


class FileNoticeProvider:
    """JSON stub: ``{"total_chips": N, "notices": [{"node": ...,
    "chips": M}, ...]}``.  The chaos rungs' wave driver."""

    def __init__(self, path: str):
        self.path = path

    def read(self) -> Optional[NoticeSignal]:
        try:
            with open(self.path) as f:
                doc = json.load(f)
            on_notice = sum(
                int(n.get("chips", 0)) for n in doc.get("notices", []))
            return NoticeSignal(on_notice, int(doc["total_chips"]))
        except (OSError, ValueError, TypeError, KeyError):
            return None  # torn mid-rewrite or absent: no signal, no write


# Taint keys that mean "this node is going away": GKE spot/preemptible
# termination, cluster-autoscaler scale-down candidates, and the
# generic unschedulable cordon that precedes a drain.
DEFAULT_TAINT_KEYS = (
    "cloud.google.com/impending-node-termination",
    "DeletionCandidateOfClusterAutoscaler",
    "ToBeDeletedByClusterAutoscaler",
    "node.kubernetes.io/unschedulable",
)


class KubectlNoticeProvider:
    """Ready nodes carrying a termination taint vs all Ready nodes,
    weighted by TPU allocatable."""

    def __init__(self, resource: str = "google.com/tpu",
                 selector: str = "",
                 taint_keys: tuple = DEFAULT_TAINT_KEYS,
                 kubectl: str = "kubectl", timeout: float = 30.0):
        self.resource = resource
        self.selector = selector
        self.taint_keys = tuple(taint_keys)
        self.kubectl = kubectl
        self.timeout = timeout

    def command(self) -> List[str]:
        cmd = [self.kubectl, "get", "nodes", "-o", "json"]
        if self.selector:
            cmd += ["-l", self.selector]
        return cmd

    @staticmethod
    def _node_ready(node: Dict) -> bool:
        for cond in node.get("status", {}).get("conditions", []):
            if cond.get("type") == "Ready":
                return cond.get("status") == "True"
        return False

    def _on_notice(self, node: Dict) -> bool:
        for taint in node.get("spec", {}).get("taints", []) or []:
            if taint.get("key") in self.taint_keys:
                return True
        return False

    def parse(self, doc: Dict) -> Optional[NoticeSignal]:
        total = on_notice = 0
        for node in doc.get("items", []):
            if not self._node_ready(node):
                continue
            alloc = node.get("status", {}).get("allocatable", {})
            try:
                chips = int(alloc.get(self.resource, 0))
            except (TypeError, ValueError):
                continue
            total += chips
            if self._on_notice(node):
                on_notice += chips
        return NoticeSignal(on_notice, total)

    def read(self) -> Optional[NoticeSignal]:
        try:
            out = subprocess.run(
                self.command(), capture_output=True, text=True,
                timeout=self.timeout, check=False)
            if out.returncode != 0:
                return None
            return self.parse(json.loads(out.stdout))
        except (OSError, subprocess.TimeoutExpired,
                json.JSONDecodeError):
            return None


def update_capacity_file(path: str, forecast: float) -> bool:
    """Read-modify-write ``preemption_forecast`` into the capacity
    file, preserving every other field.  Returns False (no write) when
    the file is absent or torn — the capacity side owns the document;
    we only annotate it."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return False
    if not isinstance(doc, dict):
        return False
    doc["preemption_forecast"] = round(max(0.0, min(1.0, float(forecast))), 6)
    doc["forecast_updated_at"] = time.time()
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(os.path.abspath(path)) or ".",
        prefix=".forecast-", suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, path)  # atomic: readers see old or new, never torn
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    return True


def publish_once(provider, capacity_file: str) -> Optional[float]:
    """One poll: read the notice signal, write the forecast.  Returns
    the forecast written, or None when held (no signal / no file)."""
    signal = provider.read()
    if signal is None:
        return None
    forecast = signal.forecast()
    if not update_capacity_file(capacity_file, forecast):
        return None
    return forecast


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--capacity-file", required=True,
                   help="operator capacity JSON to annotate")
    p.add_argument("--notices-file", default="",
                   help="JSON notice stub; empty = kubectl provider")
    p.add_argument("--selector", default="",
                   help="kubectl node label selector")
    p.add_argument("--resource", default="google.com/tpu")
    p.add_argument("--taint-keys", default=",".join(DEFAULT_TAINT_KEYS),
                   help="comma-separated taint keys meaning termination")
    p.add_argument("--interval", type=float, default=15.0)
    p.add_argument("--once", action="store_true")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.notices_file:
        provider = FileNoticeProvider(args.notices_file)
    else:
        keys = tuple(k for k in args.taint_keys.split(",") if k)
        provider = KubectlNoticeProvider(
            resource=args.resource, selector=args.selector,
            taint_keys=keys or DEFAULT_TAINT_KEYS)
    while True:
        forecast = publish_once(provider, args.capacity_file)
        if forecast is None:
            print("preemption_forecast: hold (no signal or no "
                  "capacity file)", flush=True)
        else:
            print(f"preemption_forecast: {forecast:g} -> "
                  f"{args.capacity_file}", flush=True)
        if args.once:
            return 0
        time.sleep(max(args.interval, 1.0))


if __name__ == "__main__":
    sys.exit(main())
