"""Render the training charts to concrete manifests — no helm needed.

VERDICT missing #4: the reference's chart contract is enforced by a
real ``helm install``; this environment has no helm binary, so template
bugs that the string-level checks in tests/test_orchestration.py don't
model could ship silently.  This tool closes most of that gap: it
implements the *subset* of Go-template/sprig the charts actually use
(assignments, if/else, range, include/define, the sprig calls in
_helpers.tpl), renders ``charts/maskrcnn{,-optimized}`` — main template
plus both subcharts — with a pinned release name and timestamp, and
writes the results under ``charts/golden/``.

The rendered manifests are committed; ``tests/test_golden_charts.py``
re-renders in-process and diffs against the committed files, so ANY
template or values change shows up as a reviewable manifest diff (the
property helm users get from ``helm template`` in CI).

Usage::

    python tools/render_charts.py --update     # regenerate goldens
    python tools/render_charts.py --check      # diff against goldens
"""

from __future__ import annotations

import argparse
import difflib
import os
import re
import sys

import yaml

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# Per-chart layout: the main template/values key (training charts are
# "maskrcnn", the serving chart is "serve") plus any subcharts.  The
# values-config-sync lint (eksml_tpu/analysis/checkers.py) reads this
# table too, so a new chart teaches BOTH the golden render and the
# --config key resolution in one place.
CHART_SPECS = {
    "charts/maskrcnn": {"main": "maskrcnn",
                        "subcharts": ("tensorboard", "jupyter")},
    "charts/maskrcnn-optimized": {"main": "maskrcnn",
                                  "subcharts": ("tensorboard",
                                                "jupyter")},
    "charts/serve": {"main": "serve", "subcharts": ()},
    "charts/autoscaler": {"main": "autoscaler", "subcharts": ()},
}
CHARTS = tuple(CHART_SPECS)
SUBCHARTS = ("tensorboard", "jupyter")
GOLDEN_DIR = os.path.join("charts", "golden")
# pinned render identity: goldens must be byte-stable
RELEASE = "eksml"
TIMESTAMP = "2026-01-01-00-00-00"
# install-time values an operator must supply (the charts keep them ""
# + `required`); pinned here exactly like a `helm template -f` values
# file so the goldens render and stay deterministic
GOLDEN_VALUES = {
    "maskrcnn": {"image": "REGION-docker.pkg.dev/PROJECT/eksml/"
                          "eksml-train:golden"},
    "jupyter": {"image": "REGION-docker.pkg.dev/PROJECT/eksml/"
                         "eksml-viz:golden"},
    # canary.enabled=True here (production default is off) so the
    # golden render AND the values-config-sync lint exercise the
    # canary track's template + rendered --config keys every CI run
    "serve": {"image": "REGION-docker.pkg.dev/PROJECT/eksml/"
                       "eksml-train:golden",
              "canary": {"enabled": True}},
    "autoscaler": {"image": "REGION-docker.pkg.dev/PROJECT/eksml/"
                            "eksml-train:golden"},
}


def _merge(base: dict, over: dict) -> dict:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge(out[k], v)
        else:
            out[k] = v
    return out


class RenderError(Exception):
    pass


# ---------------------------------------------------------------------
# tokenizer / parser for the Go-template subset
# ---------------------------------------------------------------------

_ACTION_RE = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.S)


def _tokenize(text: str):
    """[(kind, value)] with kind in {'text', 'action'};
    trim markers applied to neighboring text tokens (Go semantics:
    '{{-' eats preceding whitespace, '-}}' eats following)."""
    tokens = []
    pos = 0
    for m in _ACTION_RE.finditer(text):
        lead = text[pos:m.start()]
        if m.group(1) == "-":
            lead = lead.rstrip()
        if lead:
            tokens.append(("text", lead))
        tokens.append(("action", m.group(2)))
        pos = m.end()
        if m.group(3) == "-":
            while pos < len(text) and text[pos] in " \t\r\n":
                pos += 1
    tail = text[pos:]
    if tail:
        tokens.append(("text", tail))
    return tokens


def _parse(tokens, i=0, stop=("end",)):
    """Token stream → node list; returns (nodes, next_index,
    stop_keyword)."""
    nodes = []
    while i < len(tokens):
        kind, val = tokens[i]
        if kind == "text":
            nodes.append(("text", val))
            i += 1
            continue
        if val.startswith("/*"):
            i += 1
            continue
        head = val.split(None, 1)[0] if val.split() else ""
        if head in stop or head == "end":
            return nodes, i + 1, head
        if head == "if":
            body, i, stopped = _parse(tokens, i + 1,
                                      stop=("end", "else"))
            else_body = []
            if stopped == "else":
                else_body, i, _ = _parse(tokens, i, stop=("end",))
            nodes.append(("if", val.split(None, 1)[1], body, else_body))
        elif head == "range":
            body, i, _ = _parse(tokens, i + 1, stop=("end",))
            nodes.append(("range", val.split(None, 1)[1], body))
        elif head == "define":
            name = _split_args(val.split(None, 1)[1])[0].strip('"')
            body, i, _ = _parse(tokens, i + 1, stop=("end",))
            nodes.append(("define", name, body))
        elif re.match(r"^\$[\w]+\s*:?=", val):
            var, expr = re.split(r":?=", val, 1)
            nodes.append(("assign", var.strip(), expr.strip()))
            i += 1
        else:
            nodes.append(("out", val))
            i += 1
    return nodes, i, None


def _split_args(s: str):
    """Split a command on spaces, honoring quotes and parens."""
    args, buf, depth, q = [], "", 0, None
    for ch in s:
        if q:
            buf += ch
            if ch == q and not buf.endswith("\\" + q):
                q = None
            continue
        if ch in "\"'":
            q = ch
            buf += ch
        elif ch == "(":
            depth += 1
            buf += ch
        elif ch == ")":
            depth -= 1
            buf += ch
        elif ch.isspace() and depth == 0:
            if buf:
                args.append(buf)
            buf = ""
        else:
            buf += ch
    if buf:
        args.append(buf)
    return args


def _split_pipeline(s: str):
    """Split on top-level '|'."""
    parts, buf, depth, q = [], "", 0, None
    for ch in s:
        if q:
            buf += ch
            if ch == q:
                q = None
            continue
        if ch in "\"'":
            q = ch
            buf += ch
        elif ch == "(":
            depth += 1
            buf += ch
        elif ch == ")":
            depth -= 1
            buf += ch
        elif ch == "|" and depth == 0:
            parts.append(buf.strip())
            buf = ""
        else:
            buf += ch
    parts.append(buf.strip())
    return parts


_NOW = object()  # sentinel: `now`, formatted by `date`


def _is_empty(v) -> bool:
    return v in (None, "", 0, False) or (isinstance(v, (list, dict))
                                         and not v)


def _fmt_printf(fmt: str, *args):
    out, ai = "", 0
    i = 0
    while i < len(fmt):
        ch = fmt[i]
        if ch == "%" and i + 1 < len(fmt):
            spec = fmt[i + 1]
            if spec == "%":
                out += "%"
            elif spec == "q":
                out += '"%s"' % args[ai]
                ai += 1
            elif spec == "d":
                out += str(int(args[ai]))
                ai += 1
            else:  # %s and friends
                out += str(args[ai])
                ai += 1
            i += 2
            continue
        out += ch
        i += 1
    return out


class Engine:
    def __init__(self, root, helpers=None):
        self.root = root
        self.helpers = dict(helpers or {})

    # -- evaluation ----------------------------------------------------

    def _field(self, path: str, dot):
        if path == ".":
            return dot
        node = self.root if path.startswith(".") and not \
            path.startswith("..") else dot
        for part in path.strip(".").split("."):
            if part == "":
                continue
            if isinstance(node, dict) and part in node:
                node = node[part]
            else:
                raise RenderError(f"unknown field {path!r}")
        return node

    def _atom(self, tok: str, scope):
        if tok.startswith('"') and tok.endswith('"'):
            return tok[1:-1].replace('\\"', '"')
        if tok.startswith("(") and tok.endswith(")"):
            return self.eval_pipeline(tok[1:-1], scope)
        if re.fullmatch(r"-?\d+", tok):
            return int(tok)
        if tok.startswith("$"):
            if tok not in scope["vars"]:
                raise RenderError(f"undefined variable {tok}")
            return scope["vars"][tok]
        if tok.startswith("."):
            return self._field(tok, scope["dot"])
        if tok == "now":
            return _NOW
        raise RenderError(f"cannot evaluate atom {tok!r}")

    def _call(self, name: str, args, scope):
        E = _is_empty
        if name == "include":
            tpl = self.helpers.get(args[0])
            if tpl is None:
                raise RenderError(f"no template {args[0]!r}")
            return self.render_nodes(
                tpl, {"dot": args[1], "vars": {}}).strip()
        fns = {
            "int": lambda x: int(float(x)) if str(x).strip() else 0,
            "default": lambda d, v: d if E(v) else v,
            "quote": lambda v: '"%s"' % str(v).replace('"', '\\"'),
            "required": self._required,
            "printf": _fmt_printf,
            "gt": lambda a, b: a > b,
            "ge": lambda a, b: a >= b,
            "lt": lambda a, b: a < b,
            "le": lambda a, b: a <= b,
            "eq": lambda a, b: a == b,
            "ne": lambda a, b: a != b,
            "add": lambda *a: sum(a),
            "mul": lambda *a: _reduce_mul(a),
            "div": lambda a, b: int(a) // int(b),
            "mod": lambda a, b: int(a) % int(b),
            "max": lambda *a: max(int(x) for x in a),
            "min": lambda *a: min(int(x) for x in a),
            "splitList": lambda sep, s: str(s).split(sep),
            "join": lambda sep, xs: sep.join(str(x) for x in xs),
            "keys": lambda d: list(d.keys()),
            "sortAlpha": lambda xs: sorted(xs),
            "get": lambda d, k: d.get(k, ""),
            "dict": _mk_dict,
            "regexReplaceAll": lambda pat, s, repl:
                re.sub(pat, repl.replace("$", "\\"), str(s)),
            "fail": self._fail,
            "date": lambda fmt, t: TIMESTAMP,
            "not": lambda v: E(v),
            "and": lambda *a: a[-1] if all(not E(x) for x in a) else
                next((x for x in a if E(x)), a[-1]),
            "or": lambda *a: next((x for x in a if not E(x)), a[-1]),
        }
        if name not in fns:
            raise RenderError(f"unsupported function {name!r}")
        return fns[name](*args)

    @staticmethod
    def _required(msg, val):
        if _is_empty(val):
            raise RenderError(f"required value missing: {msg}")
        return val

    @staticmethod
    def _fail(msg):
        raise RenderError(f"template fail: {msg}")

    def eval_command(self, cmd: str, scope, piped=None):
        toks = _split_args(cmd)
        extra = [] if piped is None else [piped]
        head = toks[0]
        if (head[0] in '".($-' or head[0].isdigit() or head == "now") \
                and head not in ("not",):
            if len(toks) > 1 or extra:
                raise RenderError(f"cannot call value {cmd!r}")
            return self._atom(head, scope)
        args = [self._atom(t, scope) if not t[0].isalpha()
                or re.fullmatch(r"-?\d+", t) or t == "now"
                else self._maybe_atom(t, scope)
                for t in toks[1:]]
        return self._call(head, args + extra, scope)

    def _maybe_atom(self, tok, scope):
        # bare words inside calls are string literals in our subset
        # (dict keys are quoted in the charts, so this only catches
        # helper names — already quoted — and true atoms)
        try:
            return self._atom(tok, scope)
        except RenderError:
            return tok

    def eval_pipeline(self, expr: str, scope):
        val = None
        for i, cmd in enumerate(_split_pipeline(expr)):
            val = self.eval_command(cmd, scope,
                                    piped=None if i == 0 else val)
        return val

    # -- rendering -----------------------------------------------------

    def render_nodes(self, nodes, scope) -> str:
        out = []
        for node in nodes:
            kind = node[0]
            if kind == "text":
                out.append(node[1])
            elif kind == "out":
                val = self.eval_pipeline(node[1], scope)
                if val is _NOW:
                    val = TIMESTAMP
                if val is True:
                    val = "true"
                elif val is False:
                    val = "false"
                out.append("" if val is None else str(val))
            elif kind == "assign":
                scope["vars"][node[1]] = self.eval_pipeline(node[2],
                                                            scope)
            elif kind == "if":
                cond = self.eval_pipeline(node[1], scope)
                body = node[2] if not _is_empty(cond) else node[3]
                out.append(self.render_nodes(body, scope))
            elif kind == "range":
                seq = self.eval_pipeline(node[1], scope)
                for item in seq or ():
                    sub = {"dot": item, "vars": scope["vars"]}
                    out.append(self.render_nodes(node[2], sub))
            elif kind == "define":
                self.helpers[node[1]] = node[2]
        return "".join(out)

    def render(self, text: str) -> str:
        nodes, _, _ = _parse(_tokenize(text))
        # two passes so defines anywhere are visible (helm behavior)
        self.render_nodes([n for n in nodes if n[0] == "define"],
                          {"dot": self.root, "vars": {}})
        body = [n for n in nodes if n[0] != "define"]
        return self.render_nodes(body, {"dot": self.root, "vars": {}})


def _reduce_mul(args):
    out = 1
    for a in args:
        out *= int(a)
    return out


def _mk_dict(*kv):
    return {kv[i]: kv[i + 1] for i in range(0, len(kv), 2)}


# ---------------------------------------------------------------------
# chart rendering
# ---------------------------------------------------------------------

def _read(rel):
    with open(os.path.join(REPO, rel)) as f:
        return f.read()


def render_chart(chart: str) -> dict:
    """{golden filename: rendered text} for one chart dir."""
    spec = CHART_SPECS.get(chart,
                           {"main": "maskrcnn",
                            "subcharts": SUBCHARTS})
    main = spec["main"]
    values = _merge(yaml.safe_load(_read(f"{chart}/values.yaml")),
                    {main: GOLDEN_VALUES.get(main, {})})
    helpers = {}
    helpers_path = os.path.join(REPO, chart, "templates",
                                "_helpers.tpl")
    if os.path.exists(helpers_path):
        helper_nodes, _, _ = _parse(_tokenize(
            _read(f"{chart}/templates/_helpers.tpl")))
        helpers = {n[1]: n[2] for n in helper_nodes
                   if n[0] == "define"}

    out = {}
    base = os.path.basename(chart)
    eng = Engine({"Values": values, "Release": {"Name": RELEASE}},
                 helpers)
    out[f"{base}__{main}.yaml"] = eng.render(
        _read(f"{chart}/templates/{main}.yaml"))
    for sub in spec["subcharts"]:
        sub_vals = yaml.safe_load(_read(f"{chart}/charts/{sub}/values.yaml"))
        sub_vals = _merge(sub_vals, GOLDEN_VALUES.get(sub, {}))
        sub_vals["global"] = values["global"]
        sub_eng = Engine({"Values": sub_vals,
                          "Release": {"Name": RELEASE}}, helpers)
        out[f"{base}__{sub}.yaml"] = sub_eng.render(
            _read(f"{chart}/charts/{sub}/templates/{sub}.yaml"))
    return out


def render_all() -> dict:
    out = {}
    for chart in CHARTS:
        rendered = render_chart(chart)
        # every rendered manifest must be valid YAML with k8s kinds —
        # the check a helm-less CI otherwise never runs
        for name, text in rendered.items():
            docs = [d for d in yaml.safe_load_all(text) if d]
            if not docs or any("kind" not in d for d in docs):
                raise RenderError(f"{name}: rendered manifest is not "
                                  "a k8s document stream")
        out.update(rendered)
    return out


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    mode = p.add_mutually_exclusive_group(required=True)
    mode.add_argument("--update", action="store_true",
                      help="write golden manifests to charts/golden/")
    mode.add_argument("--check", action="store_true",
                      help="diff current render against the goldens")
    args = p.parse_args(argv)

    rendered = render_all()
    golden_abs = os.path.join(REPO, GOLDEN_DIR)
    if args.update:
        os.makedirs(golden_abs, exist_ok=True)
        for name, text in sorted(rendered.items()):
            dest = os.path.join(golden_abs, name)
            tmp = dest + ".tmp"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, dest)
            print(f"wrote {GOLDEN_DIR}/{name}")
        return 0
    rc = 0
    for name, text in sorted(rendered.items()):
        path = os.path.join(golden_abs, name)
        want = open(path).read() if os.path.exists(path) else ""
        if text != want:
            rc = 1
            diff = difflib.unified_diff(
                want.splitlines(True), text.splitlines(True),
                f"golden/{name}", f"rendered/{name}")
            sys.stdout.writelines(diff)
    if rc:
        print("\ngoldens stale — run: python tools/render_charts.py "
              "--update")
    else:
        print(f"{len(rendered)} golden manifests up to date")
    return rc


if __name__ == "__main__":
    sys.exit(main())
