"""Render a markdown post-mortem from a run's telemetry artifacts.

The artifacts one training logdir accumulates — ``metrics.jsonl``
(run_start-segmented scalar rows, PR 4), ``events-host<i>.jsonl``
(flight-recorder incident timeline), ``profile/attribution.json``
(component cost table, PR 3) — answer "what happened to this run?",
but only after hand-grepping three formats across N host files.  This
tool folds them into one reviewable report:

- **Run segments**: one section per ``run_start`` header (each
  relaunch in a shared logdir is a segment) with argv, config digest,
  git sha, steps covered, loss trajectory and throughput.
- **Cross-host view**: the ``hosts/*`` aggregation columns
  (min/max/mean step time, straggler index histogram) when present.
- **Incident timeline**: every flight-recorder event across all hosts,
  time-ordered — the SIGTERM → forced save → resumable exit chain, a
  NaN streak → rollback → restore chain, quarantines, pool rebuilds,
  watchdog dumps.
- **Elastic resume**: every ``checkpoint_resharded`` event — a
  restore that crossed topologies (grow/shrink relaunch) — with its
  saved→current diff; degrades to a pointer at the
  ``RESILIENCE.ELASTIC_RESUME`` knob when the run never resharded.
- **Non-finite observations**: rows whose scalars were sanitized to
  ``null`` (the ``*_raw_repr`` satellite), i.e. exactly where the loss
  went bad.
- **Goodput**: the cumulative cross-restart wall-clock ledger
  (``eksml_tpu/telemetry/goodput.py``) — per-segment goodput/badput
  buckets, between-relaunch downtime, and the effective-MFU
  composition with the banked roofline prediction.
- **Slow steps**: when the run banked span traces
  (``trace-host<i>.json``, TELEMETRY.TRACING), the cross-host merge
  names the dominant span of each outlier step — "step 412: host 3,
  1.9 s in data_wait" — via ``tools/trace_summary.py``'s merge.
- **Static SPMD cross-link**: when the logdir holds watchdog hang
  reports, the tree is audited with eksml-lint's ``collective-order``
  rule and any finding whose root→collective chain touches the
  stalled phase is flagged — the hang and the lint finding are the
  same divergence bug, proven once.
- **Concurrency cross-link**: the newest hang report's all-thread
  stalled stacks matched against eksml-lint v3's
  ``lock-order``/``blocking-under-lock`` chains — a hang whose stack
  sits inside a function a deadlock finding names is the
  statically-predicted inversion observed live; degrades to a
  pointer when no reports or findings exist.
- **Modeled cost**: the attribution component table, when the run
  banked a profile.
- **Predicted vs measured**: the perf-gate prediction bank
  (``artifacts/perf_pred_*.json``) with the calibration fit against
  banked hardware step times — degrades to a pointer at
  ``tools/perf_gate.py`` when no prediction artifact exists.

Usage::

    python tools/run_report.py <logdir> [--out report.md]
                               [--max-events 100]

Missing artifacts degrade to a note, never an error — a post-mortem
tool must work on partial evidence.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
import time
from typing import Dict, List, Optional, Tuple


def _read_jsonl(path: str) -> List[Dict]:
    rows = []
    if not os.path.exists(path):
        return rows
    with open(path) as f:
        for line in f:
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn write from a killed process
    return rows


def load_metrics(logdir: str) -> List[List[Dict]]:
    """metrics.jsonl → list of segments, split at run_start headers.
    Rows before the first header (a pre-PR-4 logdir) form segment 0
    with a synthetic header."""
    rows = _read_jsonl(os.path.join(logdir, "metrics.jsonl"))
    segments: List[List[Dict]] = []
    for row in rows:
        if row.get("event") == "run_start" or not segments:
            if row.get("event") != "run_start":
                segments.append([{"event": "run_start",
                                  "synthetic": True}])
                segments[-1].append(row)
                continue
            segments.append([row])
        else:
            segments[-1].append(row)
    return segments


def load_events(logdir: str) -> List[Dict]:
    events = []
    for path in sorted(glob.glob(
            os.path.join(logdir, "events-host*.jsonl"))):
        events.extend(_read_jsonl(path))
    events.sort(key=lambda e: e.get("time", 0.0))
    return events


def _ts(t: Optional[float]) -> str:
    if not t:
        return "-"
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(t))


def _fmt_num(v, digits=4) -> str:
    if v is None:
        return "null"
    if isinstance(v, float):
        return f"{v:.{digits}g}"
    return str(v)


def _segment_section(i: int, seg: List[Dict]) -> List[str]:
    header, rows = seg[0], [r for r in seg[1:] if "step" in r]
    lines = [f"### Segment {i + 1} — started {_ts(header.get('time'))}"]
    meta = []
    if header.get("synthetic"):
        meta.append("(rows predate the run_start header contract)")
    for key in ("git_sha", "config_digest", "host_count", "pid"):
        if key in header:
            meta.append(f"{key}=`{header[key]}`")
    if header.get("argv"):
        meta.append("argv=`" + " ".join(header["argv"]) + "`")
    if meta:
        lines.append("")
        lines.append("- " + "\n- ".join(meta))
    loss_rows = [r for r in rows if "total_loss" in r]
    if not loss_rows:
        lines.append("")
        lines.append("No training steps logged in this segment.")
        return lines
    steps = [r["step"] for r in loss_rows]
    finite = [r["total_loss"] for r in loss_rows
              if isinstance(r["total_loss"], (int, float))]
    ips = [r["images_per_sec"] for r in loss_rows
           if isinstance(r.get("images_per_sec"), (int, float))]
    lines += [
        "",
        f"- steps logged: {len(loss_rows)} "
        f"(step {min(steps)} → {max(steps)})",
        f"- total_loss: first {_fmt_num(loss_rows[0]['total_loss'])}, "
        f"last {_fmt_num(loss_rows[-1]['total_loss'])}"
        + (f", min {_fmt_num(min(finite))}" if finite else ""),
    ]
    if ips:
        lines.append(f"- images/sec: mean {_fmt_num(sum(ips)/len(ips))},"
                     f" last {_fmt_num(ips[-1])}")
    ckpt = [r for r in rows if "checkpoint_save_ms" in r
            and isinstance(r["checkpoint_save_ms"], (int, float))]
    if ckpt:
        lines.append(
            f"- checkpoint saves logged: {len(ckpt)} (last "
            f"{_fmt_num(ckpt[-1]['checkpoint_save_ms'], 5)} ms)")
    bad = [r for r in loss_rows if any(k.endswith("_raw_repr")
                                       for k in r)]
    if bad:
        items = ", ".join(
            f"step {r['step']}: "
            + "; ".join(f"{k[:-len('_raw_repr')]}={r[k]}"
                        for k in sorted(r) if k.endswith("_raw_repr"))
            for r in bad[:10])
        lines.append(f"- **non-finite scalar rows: {len(bad)}** "
                     f"({items}{', …' if len(bad) > 10 else ''})")
    agg = [r for r in loss_rows if "hosts/step_time_ms_max" in r]
    if agg:
        last = agg[-1]
        lines.append(
            "- cross-host (last interval): step_time_ms "
            f"min {_fmt_num(last.get('hosts/step_time_ms_min'))} / "
            f"mean {_fmt_num(last.get('hosts/step_time_ms_mean'))} / "
            f"max {_fmt_num(last.get('hosts/step_time_ms_max'))} over "
            f"{int(last.get('hosts/count', 1))} host(s)")
        lag: Dict[int, int] = {}
        for r in agg:
            lag[int(r.get("hosts/lagging", 0))] = lag.get(
                int(r.get("hosts/lagging", 0)), 0) + 1
        ranked = sorted(lag.items(), key=lambda kv: -kv[1])
        lines.append(
            "- straggler attribution: "
            + ", ".join(f"host {h} lagged {n}/{len(agg)} intervals"
                        for h, n in ranked[:3]))
    return lines


def _events_section(events: List[Dict], max_events: int) -> List[str]:
    lines = ["## Incident timeline (flight recorder)"]
    if not events:
        lines.append("")
        lines.append("No events-host*.jsonl found — either the run "
                     "predates the flight recorder or nothing "
                     "noteworthy happened.")
        return lines
    shown = events[-max_events:]
    lines += ["",
              f"{len(events)} event(s) recorded"
              + (f"; showing the last {len(shown)}"
                 if len(shown) < len(events) else "") + ":",
              "",
              "| time | host | kind | step | detail |",
              "|---|---|---|---|---|"]
    for e in shown:
        detail = ", ".join(
            f"{k}={e[k]}" for k in sorted(e)
            if k not in ("time", "host", "kind", "step"))
        lines.append(
            f"| {_ts(e.get('time'))} | {e.get('host', '-')} "
            f"| {e.get('kind', '?')} | {e.get('step', '-')} "
            f"| {detail or '-'} |")
    counts: Dict[str, int] = {}
    for e in events:
        counts[e.get("kind", "?")] = counts.get(e.get("kind", "?"), 0) + 1
    lines += ["",
              "By kind: " + ", ".join(
                  f"{k}×{n}" for k, n in sorted(counts.items(),
                                                key=lambda kv: -kv[1]))]
    return lines


def _elastic_section(events: List[Dict]) -> List[str]:
    """Topology-crossing restores (elastic resume, ROADMAP item 4):
    every ``checkpoint_resharded`` event with its saved→current diff,
    degrading to a pointer when the run never crossed a topology."""
    lines = ["## Elastic resume (topology changes)"]
    resharded = [e for e in events
                 if e.get("kind") == "checkpoint_resharded"]
    if not resharded:
        lines += ["", "No `checkpoint_resharded` events — every "
                      "restore (if any) matched the topology it was "
                      "saved at.  Topology-portable restore is "
                      "governed by `RESILIENCE.ELASTIC_RESUME` "
                      "(eksml_tpu/utils/checkpoint.py; per-step "
                      "topology manifests under "
                      "`checkpoints/.integrity/`)."]
        return lines
    lines += ["",
              f"{len(resharded)} resharded restore(s) — the run "
              "crossed topologies and resumed in place:",
              "",
              "| time | host | step | saved -> current |",
              "|---|---|---|---|"]
    for e in resharded:
        detail = e.get("diff") or f"{e.get('saved', '?')} -> " \
                                  f"{e.get('current', '?')}"
        lines.append(
            f"| {_ts(e.get('time'))} | {e.get('host', '-')} "
            f"| {e.get('step', '-')} | {detail} |")
    # full descriptors for the LATEST crossing only — labeled as such
    # (a grow-after-shrink run has several, all in the table above)
    lines += ["",
              f"Latest crossing: saved on {resharded[-1].get('saved', '?')}; "
              f"restored onto {resharded[-1].get('current', '?')}."]
    return lines


def _slow_steps_section(logdir: str) -> List[str]:
    """Outlier steps named by their dominant span, from the merged
    per-host span traces (telemetry tracing, ISSUE 5)."""
    lines = ["## Slow steps (span tracing)"]
    try:
        try:
            from tools import trace_summary
        except ImportError:  # script mode: tools/ is sys.path[0]
            import trace_summary
        merged = trace_summary.merge_host_traces(logdir)
    except FileNotFoundError:
        lines += ["", "No trace-host*.json found — enable "
                      "`TELEMETRY.TRACING.ENABLED` (or trigger a "
                      "`/debugz/profile` capture) to record span "
                      "timelines."]
        return lines
    except Exception as e:  # noqa: BLE001 — partial evidence is fine
        lines += ["", f"Could not merge span traces: {e!r}"]
        return lines
    if not merged["slow_steps"]:
        lines += ["", "Span traces present but no completed "
                      f"`{trace_summary.STEP_SPAN}` spans — capture "
                      "covered no full step."]
        return lines
    lines += ["",
              f"{merged['steps_covered']} step(s) traced across "
              f"{len(merged['hosts'])} host(s); mean step "
              f"{merged['mean_step_ms']} ms. Slowest:",
              "",
              "| step | slowest host | step ms | ×mean | "
              "dominant span | span ms |",
              "|---|---|---|---|---|---|"]
    for s in merged["slow_steps"]:
        lines.append(
            f"| {s['step']} | {s['host']} | {s['ms']} "
            f"| {s.get('vs_mean', '-')} "
            f"| {s.get('dominant_span', '-')} "
            f"| {s.get('dominant_ms', '-')} |")
    return lines


def _goodput_section(logdir: str) -> List[str]:
    """The cumulative cross-restart goodput ledger (ISSUE 13): per-
    segment bucket tables + the recovered between-relaunch downtime +
    the effective-MFU composition, via the SAME builder
    tools/goodput_report.py renders — degrades to a pointer on a
    logdir that predates the ledger."""
    lines = ["## Goodput (whole-run wall-clock ledger)"]
    try:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from eksml_tpu.telemetry.goodput import (BADPUT_BUCKETS,
                                                 build_ledger)
        ledger = build_ledger(logdir)
    except Exception as e:  # noqa: BLE001 — partial evidence is fine
        lines += ["", f"Could not build the goodput ledger: {e!r}"]
        return lines
    if not ledger["segments"]:
        lines += ["", ledger.get("note", "no segments"),
                  "  (`python tools/goodput_report.py <logdir>` "
                  "renders the ledger on demand; the live meter "
                  "publishes `eksml_goodput_ratio` on /metrics and "
                  "banks `goodput-host<i>.jsonl` while the run is "
                  "up — knob `TELEMETRY.GOODPUT.ENABLED`.)"]
        return lines
    lines += [
        "",
        f"{len(ledger['segments'])} segment(s) over "
        f"{_fmt_num(ledger['total_wall_s'], 6)} s wall; goodput "
        f"ratio **{ledger['goodput_ratio']}** "
        f"({_fmt_num(ledger['train_s'], 6)} s train_step; "
        f"{_fmt_num(ledger['downtime']['total_s'], 6)} s "
        "between-relaunch downtime).",
        "",
        "| segment | started | wall s | steps | mode | goodput s | "
        "top badput |",
        "|---|---|---|---|---|---|---|"]
    for seg in ledger["segments"]:
        bad = sorted(((b, seg["buckets"][b]) for b in BADPUT_BUCKETS),
                     key=lambda kv: -kv[1])
        top = ", ".join(f"{b}={v}" for b, v in bad[:3] if v > 0) or "-"
        reshard = " (resharded)" if seg.get("resharded") else ""
        lines.append(
            f"| {seg['index']}{reshard} | {_ts(seg['start'])} "
            f"| {seg['wall_s']} | {seg['steps']} | {seg['mode']} "
            f"| {seg['buckets']['train_step']} | {top} |")
    merged = ledger["buckets"]
    lines += ["", "| bucket | seconds | % of wall |", "|---|---|---|"]
    wall = ledger["total_wall_s"] or 1.0
    for b, v in sorted(merged.items(), key=lambda kv: -kv[1]):
        if v <= 0:
            continue
        lines.append(f"| {b} | {v} | {round(100 * v / wall, 2)} |")
    try:
        try:
            from tools import goodput_report
        except ImportError:  # script mode: tools/ is sys.path[0]
            import goodput_report
        mfu = goodput_report.effective_mfu(ledger["goodput_ratio"])
    except Exception as e:  # noqa: BLE001 — partial evidence is fine
        mfu = {"note": f"effective-MFU unavailable: {e!r}"}
    if "effective_mfu" in mfu:
        lines += ["",
                  f"Effective MFU: **{mfu['effective_mfu']}** = "
                  f"ideal {mfu['ideal_mfu']} "
                  f"(`{mfu['prediction']}`, {mfu['target']}) × "
                  f"goodput {mfu['goodput_ratio']}."]
    else:
        lines += ["", f"Effective MFU: {mfu['note']}"]
    return lines


def _autoscale_section(logdir: str) -> List[str]:
    """The autoscaling operator's decision trail (ISSUE 16): every
    ``decide()`` the operator banked to ``autoscale-host<i>.jsonl``,
    with the transitions (and their trainer exit codes — 77 proves
    the forced-checkpoint path) tabulated and joined against the
    goodput ledger's between-relaunch downtime.  Degrades to a
    pointer when no operator ran against this logdir."""
    lines = ["## Autoscaling (operator decision trail)"]
    rows: List[Dict] = []
    for path in sorted(glob.glob(
            os.path.join(logdir, "autoscale-host*.jsonl"))):
        try:
            with open(path) as f:
                for line in f:
                    try:
                        rows.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # torn write from a killed operator
        except OSError:
            continue
    if not rows:
        lines += ["", "No autoscale-host*.jsonl found — no operator "
                      "ran against this logdir.  "
                      "(`python tools/eksml_operator.py --logdir "
                      "<logdir> ...` banks every scale decision "
                      "here; knobs under `RESILIENCE.AUTOSCALE`.)"]
        return lines
    rows.sort(key=lambda r: r.get("time", 0.0))
    decisions = [r for r in rows if r.get("kind") == "decision"]
    actions = {a: sum(1 for d in decisions if d.get("action") == a)
               for a in ("hold", "grow", "shrink")}
    relaunches = [r for r in rows if r.get("kind") == "relaunch"]
    forced = sum(1 for r in relaunches if "exit_code" in r
                 and r["exit_code"] == 77)
    lines += [
        "",
        f"{len(decisions)} decision(s): {actions['hold']} hold, "
        f"{actions['grow']} grow, {actions['shrink']} shrink; "
        f"{len(relaunches)} relaunch(es), {forced} via the "
        "forced-checkpoint path (trainer exit 77)."]
    # the timeline keeps every transition but compresses the holds
    # (steady state is one line of counts, not hundreds of rows)
    shown = [r for r in rows if not (
        r.get("kind") == "decision" and r.get("action") == "hold")]
    if shown:
        lines += ["", "| time | kind | action | target | chips | "
                      "exit | detail |", "|---|---|---|---|---|---|"
                                         "---|"]
        for r in shown:
            detail = r.get("reason", "")
            if r.get("kind") == "relaunch" and "relaunch_gap_s" in r:
                detail = f"relaunch gap {r['relaunch_gap_s']} s"
            lines.append(
                f"| {_ts(r.get('time'))} | {r.get('kind', '-')} "
                f"| {r.get('action', '-')} | {r.get('target', '-')} "
                f"| {r.get('target_chips', '-')} "
                f"| {r.get('exit_code', '-')} | {detail} |")
    # join against the goodput ledger: what the transitions cost
    try:
        from eksml_tpu.telemetry.goodput import build_ledger

        ledger = build_ledger(logdir)
        if ledger["segments"]:
            down = ledger["downtime"]["total_s"]
            lines += [
                "",
                f"The goodput ledger attributes "
                f"{_fmt_num(down, 6)} s of between-relaunch downtime "
                f"across {len(ledger['segments'])} segment(s) — the "
                "operator's transitions are the bounded, "
                "checkpointed alternative to dying at the old "
                "topology (details in the Goodput section above)."]
    except Exception as e:  # noqa: BLE001 — partial evidence is fine
        lines += ["", f"(goodput join unavailable: {e!r})"]
    return lines


_DEPLOY_KINDS = ("serve_reload", "serve_reload_rejected",
                 "canary_score", "canary_promote", "canary_rollback")


def _deployments_section(events: List[Dict]) -> List[str]:
    """The continuous-deployment trail (ISSUE 17): every hot-reload,
    rejected candidate, shadow score and promotion/rollback the
    serving fleet and its promotion controller banked to the flight
    recorder, in one timeline.  Degrades to a pointer when no serving
    fleet ran against this logdir."""
    lines = ["## Deployments (serving hot-reload / canary)"]
    rows = [e for e in events if e.get("kind") in _DEPLOY_KINDS]
    if not rows:
        lines += ["", "No serving deployment events — no hot-reload "
                      "or canary activity against this logdir.  (The "
                      "serve pods bank `serve_reload*` events to "
                      "events-host<serve-id>.jsonl; "
                      "`python tools/eksml_operator.py --promote ...` "
                      "banks `canary_*` verdicts and actuations.)"]
        return lines
    reloads = [e for e in rows if e.get("kind") == "serve_reload"]
    rejected = [e for e in rows if e.get("kind") == "serve_reload_rejected"]
    scores = [e for e in rows if e.get("kind") == "canary_score"]
    verdicts = {v: sum(1 for e in scores if e.get("verdict") == v)
                for v in ("promote", "rollback", "hold")}
    promotions = [e for e in rows if e.get("kind") == "canary_promote"]
    rollbacks = [e for e in rows if e.get("kind") == "canary_rollback"]
    lines += [
        "",
        f"{len(reloads)} hot-reload(s), {len(rejected)} rejected "
        f"candidate(s); {len(scores)} shadow score(s) "
        f"({verdicts['promote']} promote, {verdicts['rollback']} "
        f"rollback, {verdicts['hold']} hold verdicts) -> "
        f"{len(promotions)} promotion(s), {len(rollbacks)} "
        "rollback(s) actuated."]
    # the timeline keeps every actuation/rejection but compresses the
    # hold verdicts (a steady canary is one count, not hundreds of
    # rows)
    shown = [e for e in rows if not (
        e.get("kind") == "canary_score" and e.get("verdict") == "hold")]
    if shown:
        lines += ["", "| time | host | kind | step | detail |",
                  "|---|---|---|---|---|"]
        for e in shown:
            kind = e.get("kind", "?")
            step = e.get("step", "-")
            if kind == "serve_reload":
                detail = (f"{e.get('previous_step', '?')} -> "
                          f"{e.get('step', '?')} in "
                          f"{_fmt_num(e.get('duration_ms'))} ms "
                          f"({e.get('verification', '?')})")
            elif kind == "serve_reload_rejected":
                detail = (f"reason={e.get('reason', '?')}: "
                          f"{e.get('detail', '')}"[:120])
            elif kind == "canary_score":
                detail = (f"{e.get('verdict', '?')}: "
                          f"p99_ratio={_fmt_num(e.get('p99_ratio'))} "
                          f"err={_fmt_num(e.get('error_rate'))} "
                          f"drift={_fmt_num(e.get('drift'))}")
                step = (f"{e.get('incumbent_step', '?')}/"
                        f"{e.get('canary_step', '?')}")
            elif kind == "canary_promote":
                detail = (f"{e.get('previous_step', '?')} -> "
                          f"{e.get('step', '?')} after streak "
                          f"{e.get('streak', '?')} "
                          f"(reload_ok={e.get('reload_ok', '?')})")
            elif kind == "canary_rollback":
                detail = (f"{e.get('from_step', '?')} -> "
                          f"{e.get('to_step', '?')} "
                          f"(reload_ok={e.get('reload_ok', '?')})")
                step = e.get("to_step", "-")
            else:
                detail = "-"
            lines.append(
                f"| {_ts(e.get('time'))} | {e.get('host', '-')} "
                f"| {kind} | {step} | {detail} |")
    if rejected:
        reasons: Dict[str, int] = {}
        for e in rejected:
            reasons[e.get("reason", "?")] = reasons.get(
                e.get("reason", "?"), 0) + 1
        lines += ["",
                  "Rejections by reason: " + ", ".join(
                      f"{k}×{n}" for k, n in sorted(
                          reasons.items(), key=lambda kv: -kv[1]))
                  + " — a rejected candidate leaves the old params "
                    "serving (eksml_tpu/serve/reload.py)."]
    return lines


def _attribution_section(logdir: str,
                         attribution: Optional[str]) -> List[str]:
    path = attribution or os.path.join(logdir, "profile",
                                       "attribution.json")
    lines = ["## Modeled cost by component (profile attribution)"]
    if not os.path.exists(path):
        lines += ["", f"No attribution artifact at `{path}` — run "
                      "`bench.py --profile` to bank one."]
        return lines
    try:
        with open(path) as f:
            payload = json.load(f)
        table = payload["component_table"]["component_pct"]
    except (json.JSONDecodeError, KeyError, TypeError) as e:
        lines += ["", f"Could not parse `{path}`: {e!r}"]
        return lines
    lines += ["", "| component | modeled % |", "|---|---|"]
    for comp, pct in table.items():
        lines.append(f"| {comp} | {pct} |")
    return lines


def _hang_reports(logdir: str) -> List[str]:
    """Hang reports newest-last by mtime: the names are
    hang_report_<pid>_<fires>.txt, so a lexicographic sort is
    arbitrary across restarts (pid order) and wraps within one
    process at fires=10."""
    return sorted(glob.glob(os.path.join(logdir, "hang_report_*.txt")),
                  key=os.path.getmtime)


def _scoped_lint(rules: List[str]):
    """eksml-lint findings (incl. baselined) scoped to *rules*, or an
    error string — the shared machinery of both cross-link sections.
    Two scoped calls each rebuild the whole-program graph; acceptable
    for a post-mortem tool that only lints when hang reports exist."""
    try:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from eksml_tpu.analysis import run_lint

        result = run_lint(rules=rules)
        return list(result.findings) + list(result.baselined), None
    except Exception as e:  # noqa: BLE001 — partial evidence is fine
        return [], f"Static analysis unavailable: {e!r}"


def _chain_str(fnd) -> str:
    return " → ".join(f"{c['path']}:{c['line']} {c['name']}"
                      for c in (fnd.chain or [])) or "-"


def _hang_static_section(logdir: str) -> List[str]:
    """Cross-link a watchdog hang report to a matching static
    ``collective-order`` finding (eksml-lint v2).  The lint finding
    and the hang are the same bug: a host-divergent path into (or
    around) a collective.  When a hang report names a stalled phase
    and a finding's root→collective chain touches a function whose
    name matches it, the report says so — post-mortem and prevention
    joined in one table."""
    lines = ["## Static SPMD cross-link (watchdog ↔ eksml-lint)"]
    reports = _hang_reports(logdir)
    if not reports:
        lines += ["", "No watchdog hang reports in this logdir — "
                      "nothing to cross-link.  (`python "
                      "tools/eksml_lint.py --rules collective-order "
                      "--json` audits the tree on demand.)"]
        return lines
    phase = None
    try:
        with open(reports[-1]) as f:
            for ln in f:
                if ln.startswith("stalled phase:"):
                    phase = ln.split(":", 1)[1].strip()
                    break
    except OSError:
        pass
    lines += ["", f"{len(reports)} hang report(s); newest "
                  f"`{os.path.basename(reports[-1])}` stalled in "
                  f"phase `{phase or '?'}`."]
    findings, err = _scoped_lint(["collective-order"])
    if err:
        lines += ["", err]
        return lines
    if not findings:
        lines += ["", "No static `collective-order` findings in the "
                      "tree — this hang is not the statically-"
                      "checkable divergence class (look at the "
                      "stalled phase's stack in the report; a "
                      "data-dependent skip or an external peer death "
                      "are the usual suspects)."]
        return lines
    lines += ["", "| finding | chain | matches stalled phase |",
              "|---|---|---|"]
    for fnd in findings:
        hit = bool(phase) and any(
            phase in c.get("name", "") for c in (fnd.chain or []))
        lines.append(f"| {fnd.path}:{fnd.line} "
                     f"| {_chain_str(fnd)} "
                     f"| {'**yes**' if hit else 'no'} |")
    return lines


def _stalled_stack_frames(report_path: str) -> List[Tuple[str, str, int]]:
    """(function, file-basename, line) frames from a hang report's
    all-thread stack section (``format_thread_stacks`` output:
    ``File "<path>", line N, in <func>`` pairs under ``--- thread``
    headers)."""
    frames: List[Tuple[str, str, int]] = []
    frame_re = re.compile(
        r'File "(?P<path>[^"]+)", line (?P<line>\d+), '
        r'in (?P<func>\S+)')
    try:
        with open(report_path) as f:
            for ln in f:
                m = frame_re.search(ln)
                if m:
                    frames.append((m.group("func"),
                                   os.path.basename(m.group("path")),
                                   int(m.group("line"))))
    except OSError:
        pass
    return frames


def _concurrency_section(logdir: str) -> List[str]:
    """Cross-link a watchdog hang report's stalled THREAD STACKS to a
    matching ``lock-order``/``blocking-under-lock`` finding (eksml-lint
    v3) — the thread-topology companion of the SPMD cross-link above.
    A hang whose stacks sit inside a function named by a concurrency
    finding's chain is the statically-predicted deadlock observed
    live.  Degrades to a pointer with no reports, and to an explicit
    "not this class" note with a clean tree."""
    lines = ["## Concurrency cross-link (watchdog ↔ eksml-lint v3)"]
    reports = _hang_reports(logdir)
    if not reports:
        lines += ["", "No watchdog hang reports in this logdir — "
                      "nothing to cross-link.  (`python "
                      "tools/eksml_lint.py --rules lock-order,"
                      "blocking-under-lock --json` audits the tree's "
                      "thread topology on demand.)"]
        return lines
    frames = _stalled_stack_frames(reports[-1])
    lines += ["", f"{len(reports)} hang report(s); newest "
                  f"`{os.path.basename(reports[-1])}` carries "
                  f"{len(frames)} stalled stack frame(s)."]
    findings, err = _scoped_lint(["lock-order", "blocking-under-lock"])
    if err:
        lines += ["", err]
        return lines
    if not findings:
        lines += ["", "No static `lock-order`/`blocking-under-lock` "
                      "findings in the tree — this hang is not the "
                      "statically-checkable thread-topology class "
                      "(check the stalled stacks against the data-"
                      "pipeline section; an external peer or a "
                      "wedged collective are the usual suspects)."]
        return lines
    funcs = {f for f, _, _ in frames}
    files_lines = {(b, n) for _, b, n in frames}
    lines += ["", "| finding | chain | matches stalled stack |",
              "|---|---|---|"]
    for fnd in findings:
        hit = any(
            c.get("name", "").split()[-1].rsplit(".", 1)[-1] in funcs
            or (os.path.basename(c.get("path", "")),
                c.get("line")) in files_lines
            for c in (fnd.chain or []))
        rule = getattr(fnd, "rule", "?")
        lines.append(f"| {rule}: {fnd.path}:{fnd.line} "
                     f"| {_chain_str(fnd)} "
                     f"| {'**yes**' if hit else 'no'} |")
    return lines


def _serving_section(artifacts_dir: Optional[str]) -> List[str]:
    """Serving latency/throughput from the banked load-test artifacts
    (``serve_r<N>.json``, tools/serve_loadtest.py) plus the
    span-derived slowest-request attribution the load generator
    recorded — degrades to a pointer when the serving subsystem has
    never been load-tested."""
    lines = ["## Serving (load-tested latency / throughput)"]
    if artifacts_dir is None:
        artifacts_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(
                __file__))), "artifacts")
    numbered = []
    for p in glob.glob(os.path.join(artifacts_dir, "serve_r*.json")):
        m = re.match(r"serve_r(\d+)\.json$", os.path.basename(p))
        if m:  # stray serve_r*.json names degrade to ignored, never
            numbered.append((int(m.group(1)), p))  # crash the report
    paths = [p for _, p in sorted(numbered)]
    if not paths:
        lines += ["", "No `serve_r<N>.json` artifacts in "
                      f"`{artifacts_dir}` — start the server "
                      "(`python -m eksml_tpu.serve`) and bank a "
                      "round with `python tools/serve_loadtest.py "
                      "--bank`."]
        lines.extend(_serve_predicted_lines(artifacts_dir))
        return lines
    lines += ["",
              f"{len(paths)} banked round(s):", "",
              "| round | mode | req | conc | p50 ms | p99 ms | "
              "img/s | img/s/chip | occupancy | compiles after "
              "warmup |",
              "|---|---|---|---|---|---|---|---|---|---|"]
    latest = None
    for path in paths:
        try:
            with open(path) as f:
                rec = json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            lines.append(f"| {os.path.basename(path)} | "
                         f"unreadable: {e!r} | | | | | | | | |")
            continue
        latest = rec
        lat = rec.get("latency_ms", {})
        rpc = (rec.get("engine") or {}).get("request_path_compiles")
        lines.append(
            f"| {os.path.basename(path)} | {rec.get('mode', '-')} "
            f"| {rec.get('completed', '-')} "
            f"| {rec.get('concurrency', '-')} "
            f"| {lat.get('p50', '-')} | {lat.get('p99', '-')} "
            f"| {rec.get('images_per_sec', '-')} "
            f"| {rec.get('images_per_sec_per_chip', '-')} "
            f"| {rec.get('batch_occupancy_mean', '-')} "
            f"| {'**' + str(rpc) + '**' if rpc else rpc} |")
    if latest is None:
        return lines
    phases = latest.get("phase_ms", {})
    if phases:
        lines += ["", "Latest round's phase attribution "
                      "(span-derived, per request):", "",
                  "| phase | mean ms | p99 ms |", "|---|---|---|"]
        for ph in ("queue_wait", "pad", "device_infer",
                   "postprocess"):
            row = phases.get(ph) or {}
            lines.append(f"| {ph} | {row.get('mean', '-')} "
                         f"| {row.get('p99', '-')} |")
    slowest = latest.get("slowest") or ()
    if slowest:
        lines += ["", "Slowest requests (dominant span named — the "
                      "tail is attributable, not a bare number):", "",
                  "| req | total ms | dominant span | queue_wait | "
                  "device_infer | bucket | fill/rung |",
                  "|---|---|---|---|---|---|---|"]
        for s in slowest[:5]:
            ph = s.get("phases", {})
            bucket = s.get("bucket")
            lines.append(
                f"| {s.get('idx', '-')} "
                f"| {round(s.get('total_ms', 0), 1)} "
                f"| **{s.get('dominant_phase', '-')}** "
                f"| {ph.get('queue_wait', '-')} "
                f"| {ph.get('device_infer', '-')} "
                f"| {'x'.join(str(b) for b in bucket) if bucket else '-'} "
                f"| {s.get('batch_fill', '-')}/"
                f"{s.get('batch_rung', '-')} |")
    lines.extend(_serve_predicted_lines(artifacts_dir))
    return lines


def _serve_predicted_lines(artifacts_dir: str) -> List[str]:
    """The hermetic per-bucket predicted-latency bank
    (``perf_pred_serve_*``, tools/perf_gate.py --serve) — rendered
    under Serving, NOT in the train-step table (an inference program
    has no bwd/comms/optimizer)."""
    preds = sorted(glob.glob(os.path.join(
        artifacts_dir, "perf_pred_serve_*.json")))
    if not preds:
        return []
    lines = ["", "Predicted device latency per (bucket, batch) rung "
                 "(`tools/perf_gate.py --serve`, smoke widths — "
                 "ratios, not absolutes):", "",
             "| key | predicted ms | per image ms |", "|---|---|---|"]
    for path in preds:
        try:
            with open(path) as f:
                rec = json.load(f)
            lines.append(
                f"| {rec.get('key', os.path.basename(path))} "
                f"| {rec.get('predicted_latency_ms', '-')} "
                f"| {rec.get('predicted_latency_per_image_ms', '-')}"
                " |")
        except (json.JSONDecodeError, OSError) as e:
            lines.append(f"| {os.path.basename(path)} "
                         f"| unreadable: {e!r} | |")
    return lines


def _predicted_section(artifacts_dir: Optional[str]) -> List[str]:
    """Predicted-vs-measured step-time table from the perf-gate bank
    (ISSUE 7), degrading to a pointer exactly like the span-tracing
    table when no prediction artifact exists."""
    lines = ["## Predicted vs measured step time (perf gate)"]
    if artifacts_dir is None:
        artifacts_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(
                __file__))), "artifacts")
    preds = sorted(glob.glob(os.path.join(artifacts_dir,
                                          "perf_pred_*.json")))
    # serving predictions (perf_pred_serve_*) price the INFERENCE
    # program — fwd/bwd/comms/optimizer rows would be meaningless in
    # this TRAIN-step table; they render in the Serving section
    preds = [p for p in preds if not os.path.basename(p)
             .startswith("perf_pred_serve_")]
    if not preds:
        lines += ["", "No `perf_pred_*.json` prediction artifacts in "
                      f"`{artifacts_dir}` — run `python "
                      "tools/perf_gate.py --update-baseline` to bank "
                      "the hermetic roofline predictions."]
        return lines
    lines += ["",
              f"{len(preds)} banked prediction(s) (smoke-width "
              "lowering — compare ratios, not absolutes):", "",
              "| key | predicted ms | fwd | bwd | comms | optimizer |",
              "|---|---|---|---|---|---|"]
    for path in preds:
        try:
            with open(path) as f:
                rec = json.load(f)
            s = rec.get("sections_ms", {})
            lines.append(
                f"| {rec.get('key', os.path.basename(path))} "
                f"| {rec.get('predicted_step_time_ms', '-')} "
                f"| {s.get('fwd', '-')} | {s.get('bwd', '-')} "
                f"| {s.get('comms', '-')} "
                f"| {s.get('optimizer', '-')} |")
        except (json.JSONDecodeError, OSError) as e:
            lines.append(f"| {os.path.basename(path)} | "
                         f"unreadable: {e!r} | | | | |")
    try:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from eksml_tpu.profiling.predict import (calibrate,
                                                 calibration_points)

        cal = calibrate(calibration_points(artifacts_dir))
    except Exception as e:  # noqa: BLE001 — partial evidence is fine
        lines += ["", f"Calibration unavailable: {e!r}"]
        return lines
    if not cal["points"]:
        lines += ["", "No measured-vs-predicted calibration pairs "
                      "yet — the fit tightens when a hardware round "
                      "lands (bench.py emits predicted alongside "
                      "measured)."]
        return lines
    lines += ["",
              f"Calibration over {cal['n_points']} hardware "
              f"point(s): scale {cal['scale']}x, model error "
              f"{cal['model_error_pct']}% (max per-rung deviation "
              "from the common fit):", "",
              "| rung | measured ms | predicted ms | scale | "
              "deviation |",
              "|---|---|---|---|---|"]
    for pt in cal["points"]:
        lines.append(
            f"| {pt['rung']} | {pt['measured_ms']} "
            f"| {pt['predicted_ms']} | {pt['scale']} "
            f"| {pt['deviation_pct']}% |")
    return lines


def _comms_section(artifacts_dir: Optional[str]) -> List[str]:
    """Communication observatory (ISSUE 19): per-link totals and the
    top exposed collectives from the per-collective ledgers banked
    inside ``perf_pred_*`` artifacts — degrading to a pointer exactly
    like the predicted-step-time table when no banked prediction
    carries a ledger yet."""
    lines = ["## Communication (predicted per-collective ledger)"]
    if artifacts_dir is None:
        artifacts_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(
                __file__))), "artifacts")
    preds = sorted(glob.glob(os.path.join(artifacts_dir,
                                          "perf_pred_*.json")))
    preds = [p for p in preds if not os.path.basename(p)
             .startswith("perf_pred_serve_")]
    recs = []
    for path in preds:
        try:
            with open(path) as f:
                rec = json.load(f)
        except (json.JSONDecodeError, OSError):
            continue
        if rec.get("comms_ms") and rec.get("collectives"):
            recs.append((rec.get("key", os.path.basename(path)), rec))
    if not recs:
        lines += ["", "No banked prediction carries a per-collective "
                      f"ledger in `{artifacts_dir}` — run `python "
                      "tools/perf_gate.py --update-baseline` to bank "
                      "replica_groups-exact predictions."]
        return lines
    lines += ["",
              "Per-link predicted collective time per banked rung "
              "(replica_groups-exact pricing; exposed = not hidden "
              "behind compute in an async start/done window — the "
              "overlap headroom):", "",
              "| key | ici ms | dcn ms | exposed ms | exposed dcn "
              "ms |", "|---|---|---|---|---|"]
    for key, rec in recs:
        c = rec["comms_ms"]
        lines.append(
            f"| {key} | {c.get('ici_ms', '-')} "
            f"| {c.get('dcn_ms', '-')} | {c.get('exposed_ms', '-')} "
            f"| {c.get('exposed_dcn_ms', '-')} |")
    top = []
    for key, rec in recs:
        for row in rec["collectives"]:
            if row.get("exposed_ms", 0) > 0:
                top.append((key, row))
    top.sort(key=lambda kr: -kr[1]["exposed_ms"])
    if top:
        lines += ["", "Top exposed collectives (the overlap PR's "
                      "targets, worst first):", "",
                  "| key | collective | opcode | component | link | "
                  "group | bytes | predicted ms | exposed ms |",
                  "|---|---|---|---|---|---|---|---|---|"]
        for key, row in top[:8]:
            lines.append(
                f"| {key} | {row.get('name', '-')} "
                f"| {row.get('opcode', '-')} "
                f"| {row.get('component', '-')} "
                f"| {row.get('link', '-')} "
                f"| {row.get('num_groups', '-')}x"
                f"{row.get('group_size', '-')} "
                f"| {row.get('bytes', '-')} "
                f"| {row.get('predicted_ms', '-')} "
                f"| {row.get('exposed_ms', '-')} |")
    return lines


def _memory_section(artifacts_dir: Optional[str]) -> List[str]:
    """HBM observatory (ISSUE 20): liveness-predicted peak HBM per
    banked rung with capacity headroom and the top live-at-peak
    components — degrading to a pointer exactly like the comms table
    when no banked prediction carries an ``hbm`` section yet.
    Includes serve rungs: the serving capacity claim is a memory
    statement too."""
    lines = ["## Memory (predicted peak HBM, liveness model)"]
    if artifacts_dir is None:
        artifacts_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(
                __file__))), "artifacts")
    preds = sorted(glob.glob(os.path.join(artifacts_dir,
                                          "perf_pred_*.json")))
    recs = []
    for path in preds:
        try:
            with open(path) as f:
                rec = json.load(f)
        except (json.JSONDecodeError, OSError):
            continue
        if (rec.get("hbm") or {}).get("peak_hbm_bytes"):
            recs.append((rec.get("key", os.path.basename(path)), rec))
    if not recs:
        lines += ["", "No banked prediction carries an `hbm` section "
                      f"in `{artifacts_dir}` — run `python "
                      "tools/perf_gate.py --update-baseline` to bank "
                      "liveness-based peak-memory predictions."]
        return lines
    lines += ["",
              "Liveness-predicted peak HBM per banked rung (define at "
              "producer, free after last use; donation credited; "
              "upper-ish bound — XLA may rematerialize under "
              "pressure):", "",
              "| key | peak MB | capacity MB | headroom MB | util % | "
              "top live-at-peak |", "|---|---|---|---|---|---|"]
    for key, rec in recs:
        h = rec["hbm"]
        cap = h.get("capacity") or {}
        comps = h.get("live_at_peak_by_component") or {}
        top = ", ".join(f"{k} {v / 1e6:.1f}MB"
                        for k, v in list(comps.items())[:3])
        lines.append(
            f"| {key} | {h['peak_hbm_bytes'] / 1e6:.1f} "
            f"| {cap.get('hbm_bytes', 0) / 1e6:.0f} "
            f"| {cap.get('headroom_bytes', 0) / 1e6:.1f} "
            f"| {cap.get('utilization_pct', '-')} "
            f"| {top or '-'} |")
    return lines


def render_report(logdir: str, attribution: Optional[str] = None,
                  max_events: int = 100,
                  artifacts_dir: Optional[str] = None) -> str:
    segments = load_metrics(logdir)
    events = load_events(logdir)
    lines = [f"# Run report — `{logdir}`", "",
             f"Generated {_ts(time.time())} by tools/run_report.py.",
             "", "## Run segments"]
    if not segments:
        lines += ["", "No metrics.jsonl found — nothing was logged "
                      "(or the logdir path is wrong)."]
    for i, seg in enumerate(segments):
        lines.append("")
        lines.extend(_segment_section(i, seg))
    lines.append("")
    lines.extend(_events_section(events, max_events))
    lines.append("")
    lines.extend(_elastic_section(events))
    lines.append("")
    lines.extend(_goodput_section(logdir))
    lines.append("")
    lines.extend(_autoscale_section(logdir))
    lines.append("")
    lines.extend(_deployments_section(events))
    lines.append("")
    lines.extend(_slow_steps_section(logdir))
    lines.append("")
    lines.extend(_hang_static_section(logdir))
    lines.append("")
    lines.extend(_concurrency_section(logdir))
    lines.append("")
    lines.extend(_attribution_section(logdir, attribution))
    lines.append("")
    lines.extend(_predicted_section(artifacts_dir))
    lines.append("")
    lines.extend(_comms_section(artifacts_dir))
    lines.append("")
    lines.extend(_memory_section(artifacts_dir))
    lines.append("")
    lines.extend(_serving_section(artifacts_dir))
    lines.append("")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("logdir", help="training run directory")
    p.add_argument("--out", default=None,
                   help="write the report here (default: stdout)")
    p.add_argument("--attribution", default=None,
                   help="attribution.json path (default: "
                        "<logdir>/profile/attribution.json)")
    p.add_argument("--max-events", type=int, default=100,
                   help="cap on timeline rows (newest kept)")
    p.add_argument("--artifacts", default=None,
                   help="perf-gate artifact dir for the predicted-vs-"
                        "measured table (default: <repo>/artifacts)")
    args = p.parse_args(argv)

    report = render_report(args.logdir, attribution=args.attribution,
                           max_events=args.max_events,
                           artifacts_dir=args.artifacts)
    if args.out:
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            f.write(report)
        os.replace(tmp, args.out)
        print(f"wrote {args.out}")
    else:
        sys.stdout.write(report)
    return 0


if __name__ == "__main__":
    sys.exit(main())
