"""Closed+open-loop load generator for the online serving subsystem.

Drives ``POST /v1/predict`` on a running server (``python -m
eksml_tpu.serve``) with seeded synthetic images of mixed sizes and
banks an ``artifacts/serve_r<N>.json`` latency/throughput artifact
next to the training ladder — the serving half of the repo's
banked-evidence rule (artifacts/README.md):

- **closed loop** (default): ``--concurrency`` workers each issue
  requests back-to-back until ``--requests`` complete — measures the
  server's throughput ceiling and the latency AT that ceiling.
- **open loop** (``--mode open --rate R``): requests fire on a fixed
  arrival schedule regardless of completions — measures latency under
  a *given* offered load, the way real user traffic behaves
  (closed-loop latency hides queueing collapse; open-loop exposes it).

Every record carries the server's span-derived ``timings_ms`` phase
breakdown (queue_wait / pad / device_infer / postprocess), so the
artifact attributes tail latency to a phase, and the post-run
``/healthz`` scrape pins the engine's compile counters — the banked
proof that the request path compiled NOTHING after warmup.

Usage::

    python tools/serve_loadtest.py --url http://127.0.0.1:8081 \\
        --requests 200 --concurrency 8 --bank
    python tools/serve_loadtest.py --port-file /tmp/serve.port \\
        --mode open --rate 50 --requests 500 --out artifacts/serve_r2.json
"""

from __future__ import annotations

import argparse
import base64
import glob
import json
import os
import queue
import re
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from eksml_tpu.fsio import atomic_write_json  # noqa: E402

PHASES = ("queue_wait", "pad", "device_infer", "postprocess")

DEFAULT_SIZES = "480x640,640x480,330x500,600x400,512x512"


def _utcnow() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def gen_image(seed: int, idx: int, sizes: List[Tuple[int, int]]
              ) -> np.ndarray:
    """Deterministic synthetic uint8 RGB image for request ``idx``."""
    rng = np.random.RandomState(seed + idx)
    h, w = sizes[idx % len(sizes)]
    return rng.randint(0, 255, (h, w, 3)).astype(np.uint8)


def post_predict(url: str, image: np.ndarray, timeout: float = 120.0,
                 score_thresh: Optional[float] = None) -> Dict:
    """One request; returns the decoded response with ``_latency_ms``
    (client-observed) added.  Raises ``urllib.error.HTTPError`` on a
    non-2xx answer."""
    payload: Dict = {
        "image_b64": base64.b64encode(image.tobytes()).decode("ascii"),
        "shape": list(image.shape),
        "dtype": "uint8",
    }
    if score_thresh is not None:
        payload["score_thresh"] = score_thresh
    body = json.dumps(payload).encode("utf-8")
    req = urllib.request.Request(
        url.rstrip("/") + "/v1/predict", data=body,
        headers={"Content-Type": "application/json"})
    t0 = time.perf_counter()
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        out = json.loads(resp.read().decode("utf-8"))
    out["_latency_ms"] = (time.perf_counter() - t0) * 1e3
    return out


def fetch_health(url: str, timeout: float = 10.0) -> Dict:
    """``/healthz`` payload regardless of status code (503 while
    warming/draining still carries the state fields)."""
    try:
        with urllib.request.urlopen(url.rstrip("/") + "/healthz",
                                    timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        return json.loads(e.read().decode("utf-8"))


def wait_ready(url: str, budget: float = 600.0) -> Dict:
    """Poll ``/healthz`` until it reports ``ok`` (warmup done)."""
    deadline = time.monotonic() + budget
    last: Dict = {}
    while time.monotonic() < deadline:
        try:
            last = fetch_health(url)
            if last.get("status") == "ok":
                return last
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(0.25)
    raise TimeoutError(
        f"server at {url} not ready within {budget}s "
        f"(last /healthz: {last})")


def metric_value(metrics_text: str, name: str,
                 labels: str = "") -> Optional[float]:
    """First sample value of ``name{labels}`` in an OpenMetrics body."""
    pat = re.compile(r"^" + re.escape(name)
                     + (re.escape(labels) if labels else r"(?:\{[^}]*\})?")
                     + r" (\S+)$", re.M)
    m = pat.search(metrics_text)
    return float(m.group(1)) if m else None


def scrape_metrics(url: str, timeout: float = 10.0) -> str:
    with urllib.request.urlopen(url.rstrip("/") + "/metrics",
                                timeout=timeout) as resp:
        return resp.read().decode("utf-8")


def _pct(values: List[float], q: float) -> float:
    return float(np.percentile(np.asarray(values), q)) if values else 0.0


def run_load(url: str, requests: int, concurrency: int,
             mode: str = "closed", rate: float = 0.0, seed: int = 0,
             sizes: str = DEFAULT_SIZES,
             timeout: float = 120.0) -> Dict:
    """Drive the load and fold the records into the artifact dict."""
    size_list = [tuple(int(d) for d in s.split("x"))
                 for s in sizes.split(",") if s]
    records: List[Dict] = []
    errors: List[str] = []
    slips_ms: List[float] = []
    rec_lock = threading.Lock()
    work: "queue.Queue" = queue.Queue()
    for i in range(requests):
        work.put(i)
    # open loop needs headroom beyond the closed-loop worker count:
    # with only `concurrency` workers, arrivals silently throttle to
    # the completion rate the moment latency exceeds the inter-arrival
    # gap — coordinated omission, the exact bias open loop exists to
    # avoid.  Workers auto-size (concurrency stays a floor) and any
    # residual schedule slip is MEASURED and banked, never hidden.
    n_workers = (max(1, concurrency) if mode != "open"
                 else min(requests, max(concurrency, 64)))
    t_start = time.perf_counter()

    def one(idx: int) -> None:
        if mode == "open" and rate > 0:
            # fixed arrival schedule: request idx fires at idx/rate
            # seconds after start, whatever the completions are doing
            delay = t_start + idx / rate - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            else:
                with rec_lock:
                    slips_ms.append(-delay * 1e3)
        img = gen_image(seed, idx, size_list)
        try:
            resp = post_predict(url, img, timeout=timeout)
        except Exception as e:  # noqa: BLE001 — recorded, not fatal
            with rec_lock:
                errors.append(f"req {idx}: {e!r}")
            return
        with rec_lock:
            records.append({
                "idx": idx,
                "total_ms": resp["_latency_ms"],
                "phases": {k: resp.get("timings_ms", {}).get(k)
                           for k in PHASES},
                "bucket": resp.get("bucket"),
                "batch_fill": resp.get("batch_fill"),
                "batch_rung": resp.get("batch_rung"),
                "detections": len(resp.get("detections", ())),
            })

    def worker() -> None:
        while True:
            try:
                idx = work.get_nowait()
            except queue.Empty:
                return
            one(idx)

    threads = [threading.Thread(target=worker, daemon=True,
                                name=f"loadgen-{i}")
               for i in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t_start

    lat = [r["total_ms"] for r in records]
    phase_ms = {}
    for ph in PHASES:
        vals = [r["phases"][ph] for r in records
                if isinstance(r["phases"].get(ph), (int, float))]
        phase_ms[ph] = {"mean": round(float(np.mean(vals)), 3)
                        if vals else None,
                        "p99": round(_pct(vals, 99), 3)
                        if vals else None}
    fills = [r["batch_fill"] / r["batch_rung"] for r in records
             if r.get("batch_rung")]
    slowest = sorted(records, key=lambda r: -r["total_ms"])[:5]
    for s in slowest:
        ph = {k: v for k, v in s["phases"].items()
              if isinstance(v, (int, float))}
        s["dominant_phase"] = (max(ph, key=ph.get) if ph else None)
    open_loop = None
    if mode == "open":
        behind = [s for s in slips_ms if s > 5.0]
        open_loop = {
            "workers": n_workers,
            "arrivals_behind": len(behind),
            "slip_ms": {
                "mean": round(float(np.mean(slips_ms)), 3)
                if slips_ms else 0.0,
                "p99": round(_pct(slips_ms, 99), 3)
                if slips_ms else 0.0,
                "max": round(max(slips_ms), 3) if slips_ms else 0.0,
            },
            # nonzero arrivals_behind = the offered rate was NOT
            # fully sustained (worker pool or client box saturated);
            # the latency numbers then understate the true open-loop
            # tail — read them as a lower bound
            "offered_rate_sustained": not behind,
        }
    return {
        "kind": "serve_loadtest",
        "mode": mode,
        "rate_rps": rate if mode == "open" else None,
        "open_loop": open_loop,
        "requests": requests,
        "completed": len(records),
        "errors": len(errors),
        "error_samples": errors[:5],
        "concurrency": concurrency,
        "sizes": sizes,
        "seed": seed,
        "wall_s": round(wall_s, 3),
        "images_per_sec": round(len(records) / wall_s, 3)
        if wall_s > 0 else 0.0,
        "latency_ms": {
            "p50": round(_pct(lat, 50), 3),
            "p90": round(_pct(lat, 90), 3),
            "p99": round(_pct(lat, 99), 3),
            "mean": round(float(np.mean(lat)), 3) if lat else 0.0,
            "max": round(max(lat), 3) if lat else 0.0,
        },
        "phase_ms": phase_ms,
        "batch_occupancy_mean": round(float(np.mean(fills)), 3)
        if fills else None,
        "slowest": slowest,
    }


def next_bank_path(artifacts_dir: str) -> str:
    """First free ``serve_r<N>.json`` slot."""
    taken = set()
    for p in glob.glob(os.path.join(artifacts_dir, "serve_r*.json")):
        m = re.match(r"serve_r(\d+)\.json$", os.path.basename(p))
        if m:
            taken.add(int(m.group(1)))
    n = 1
    while n in taken:
        n += 1
    return os.path.join(artifacts_dir, f"serve_r{n}.json")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--url", default=None,
                   help="server base URL, e.g. http://127.0.0.1:8081")
    p.add_argument("--port-file", default=None,
                   help="read the port from this file (the --port-file "
                        "the server wrote) and target 127.0.0.1")
    p.add_argument("--requests", type=int, default=200)
    p.add_argument("--concurrency", type=int, default=8)
    p.add_argument("--mode", choices=["closed", "open"],
                   default="closed")
    p.add_argument("--rate", type=float, default=0.0,
                   help="open-loop arrival rate (requests/sec)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--sizes", default=DEFAULT_SIZES,
                   help="comma list of HxW request image sizes "
                        "[%(default)s]")
    p.add_argument("--timeout", type=float, default=120.0)
    p.add_argument("--wait-ready", type=float, default=600.0,
                   help="seconds to wait for /healthz ok before load")
    p.add_argument("--out", default=None,
                   help="write the artifact here (atomic)")
    p.add_argument("--bank", action="store_true",
                   help="write to the next free "
                        "artifacts/serve_r<N>.json slot")
    p.add_argument("--note", default=None,
                   help="free-text provenance recorded in the "
                        "artifact (geometry, hardware, caveats)")
    args = p.parse_args(argv)

    if args.url:
        url = args.url
    elif args.port_file:
        deadline = time.monotonic() + args.wait_ready
        while not os.path.exists(args.port_file):
            if time.monotonic() > deadline:
                p.error(f"port file {args.port_file} never appeared")
            time.sleep(0.2)
        url = f"http://127.0.0.1:{open(args.port_file).read().strip()}"
    else:
        p.error("need --url or --port-file")
    if args.mode == "open" and args.rate <= 0:
        p.error("--mode open needs --rate > 0")

    health = wait_ready(url, budget=args.wait_ready)
    artifact = run_load(url, args.requests, args.concurrency,
                        mode=args.mode, rate=args.rate, seed=args.seed,
                        sizes=args.sizes, timeout=args.timeout)
    # post-run engine state: the zero-cold-compile proof and the
    # per-chip normalization ride the SAME scrape the HPA uses
    try:
        post = fetch_health(url)
        metrics = scrape_metrics(url)
    except (urllib.error.URLError, OSError) as e:
        post, metrics = {"error": repr(e)}, ""
    devices = int(post.get("devices") or health.get("devices") or 1)
    artifact.update({
        "url": url,
        "devices": devices,
        "images_per_sec_per_chip": round(
            artifact["images_per_sec"] / max(devices, 1), 3),
        "engine": {
            "compiles": post.get("compiles"),
            "request_path_compiles": post.get("request_path_compiles"),
            "warm_executables": post.get("warm_executables"),
            "buckets": post.get("buckets"),
            "batch_rungs": post.get("batch_rungs"),
        },
        "zero_request_path_compiles":
            post.get("request_path_compiles") == 0,
        "metrics": {
            "requests_ok": metric_value(
                metrics, "eksml_serve_requests_total",
                '{outcome="ok"}'),
            "batches": metric_value(metrics,
                                    "eksml_serve_batches_total"),
            "aot_compiles": metric_value(
                metrics, "eksml_serve_aot_compiles_total"),
            "request_path_compiles": metric_value(
                metrics, "eksml_serve_request_path_compiles_total"),
        },
        "banked_at": _utcnow(),
    })
    if args.note:
        artifact["note"] = args.note
    payload = json.dumps(artifact, indent=1)
    print(payload)
    out = args.out
    if out is None and args.bank:
        out = next_bank_path(os.path.join(REPO, "artifacts"))
    if out:
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        atomic_write_json(out, artifact)
        print(f"banked {out}", file=sys.stderr)
    return 0 if artifact["errors"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
