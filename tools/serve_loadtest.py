"""Closed+open-loop load generator for the online serving subsystem.

Drives ``POST /v1/predict`` on a running server (``python -m
eksml_tpu.serve``) with seeded synthetic images of mixed sizes and
banks an ``artifacts/serve_r<N>.json`` latency/throughput artifact
next to the training ladder — the serving half of the repo's
banked-evidence rule (artifacts/README.md):

- **closed loop** (default): ``--concurrency`` workers each issue
  requests back-to-back until ``--requests`` complete — measures the
  server's throughput ceiling and the latency AT that ceiling.
- **open loop** (``--mode open --rate R``): requests fire on a fixed
  arrival schedule regardless of completions — measures latency under
  a *given* offered load, the way real user traffic behaves
  (closed-loop latency hides queueing collapse; open-loop exposes it).

Every record carries the server's span-derived ``timings_ms`` phase
breakdown (queue_wait / pad / device_infer / postprocess), so the
artifact attributes tail latency to a phase, and the post-run
``/healthz`` scrape pins the engine's compile counters — the banked
proof that the request path compiled NOTHING after warmup.

**Record / replay / shadow** (the canary-scoring harness): ``--record``
banks the request DISTRIBUTION (seed + per-request shapes — the
regenerable form, kilobytes not gigabytes) so the exact same traffic
replays later; ``--replay BANK --shadow --canary-url URL`` mirrors
every banked request at both the incumbent and the canary and scores
the canary on three axes — latency p99 ratio, error rate, and
detection-output drift (pre-threshold ``raw_top`` head outputs, so
drift is exactly 0 for identical params and nonzero for different
ones even when neither side clears the score threshold).  The score
artifact banks as ``artifacts/shadow_r<N>.json``; the promotion
controller (``tools/eksml_operator.py --promote``) consumes the same
``replay_shadow`` call to gate promote-vs-rollback.

Usage::

    python tools/serve_loadtest.py --url http://127.0.0.1:8081 \\
        --requests 200 --concurrency 8 --bank
    python tools/serve_loadtest.py --port-file /tmp/serve.port \\
        --mode open --rate 50 --requests 500 --out artifacts/serve_r2.json
    python tools/serve_loadtest.py --record /tmp/bank.json --requests 100
    python tools/serve_loadtest.py --url http://stable:8081 \\
        --replay /tmp/bank.json --shadow --canary-url http://canary:8081
"""

from __future__ import annotations

import argparse
import base64
import glob
import json
import os
import queue
import re
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from eksml_tpu.fsio import atomic_write_json  # noqa: E402

PHASES = ("queue_wait", "pad", "device_infer", "postprocess")

DEFAULT_SIZES = "480x640,640x480,330x500,600x400,512x512"


def _utcnow() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def gen_image(seed: int, idx: int, sizes: List[Tuple[int, int]]
              ) -> np.ndarray:
    """Deterministic synthetic uint8 RGB image for request ``idx``."""
    rng = np.random.RandomState(seed + idx)
    h, w = sizes[idx % len(sizes)]
    return rng.randint(0, 255, (h, w, 3)).astype(np.uint8)


def post_predict(url: str, image: np.ndarray, timeout: float = 120.0,
                 score_thresh: Optional[float] = None,
                 raw_topk: int = 0) -> Dict:
    """One request; returns the decoded response with ``_latency_ms``
    (client-observed) added.  Raises ``urllib.error.HTTPError`` on a
    non-2xx answer.  ``raw_topk`` asks the server for its
    pre-threshold top-k raw head outputs (the drift signal)."""
    payload: Dict = {
        "image_b64": base64.b64encode(image.tobytes()).decode("ascii"),
        "shape": list(image.shape),
        "dtype": "uint8",
    }
    if score_thresh is not None:
        payload["score_thresh"] = score_thresh
    if raw_topk:
        payload["raw_topk"] = int(raw_topk)
    body = json.dumps(payload).encode("utf-8")
    req = urllib.request.Request(
        url.rstrip("/") + "/v1/predict", data=body,
        headers={"Content-Type": "application/json"})
    t0 = time.perf_counter()
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        out = json.loads(resp.read().decode("utf-8"))
    out["_latency_ms"] = (time.perf_counter() - t0) * 1e3
    return out


def fetch_health(url: str, timeout: float = 10.0) -> Dict:
    """``/healthz`` payload regardless of status code (503 while
    warming/draining still carries the state fields)."""
    try:
        with urllib.request.urlopen(url.rstrip("/") + "/healthz",
                                    timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        return json.loads(e.read().decode("utf-8"))


def wait_ready(url: str, budget: float = 600.0) -> Dict:
    """Poll ``/healthz`` until it reports ``ok`` (warmup done)."""
    deadline = time.monotonic() + budget
    last: Dict = {}
    while time.monotonic() < deadline:
        try:
            last = fetch_health(url)
            if last.get("status") == "ok":
                return last
        except (urllib.error.URLError, OSError):
            pass
        time.sleep(0.25)
    raise TimeoutError(
        f"server at {url} not ready within {budget}s "
        f"(last /healthz: {last})")


def metric_value(metrics_text: str, name: str,
                 labels: str = "") -> Optional[float]:
    """First sample value of ``name{labels}`` in an OpenMetrics body."""
    pat = re.compile(r"^" + re.escape(name)
                     + (re.escape(labels) if labels else r"(?:\{[^}]*\})?")
                     + r" (\S+)$", re.M)
    m = pat.search(metrics_text)
    return float(m.group(1)) if m else None


def scrape_metrics(url: str, timeout: float = 10.0) -> str:
    with urllib.request.urlopen(url.rstrip("/") + "/metrics",
                                timeout=timeout) as resp:
        return resp.read().decode("utf-8")


def _pct(values: List[float], q: float) -> float:
    return float(np.percentile(np.asarray(values), q)) if values else 0.0


def run_load(url: str, requests: int, concurrency: int,
             mode: str = "closed", rate: float = 0.0, seed: int = 0,
             sizes: str = DEFAULT_SIZES,
             timeout: float = 120.0,
             keep_records: bool = False) -> Dict:
    """Drive the load and fold the records into the artifact dict.
    ``keep_records=True`` adds the raw per-request records (t_wall +
    params_step included) — the hot-reload chaos rung joins them
    against the ``serve_reload`` flight event to prove the swap
    boundary; banked artifacts stay summary-only."""
    size_list = [tuple(int(d) for d in s.split("x"))
                 for s in sizes.split(",") if s]
    records: List[Dict] = []
    errors: List[str] = []
    slips_ms: List[float] = []
    rec_lock = threading.Lock()
    work: "queue.Queue" = queue.Queue()
    for i in range(requests):
        work.put(i)
    # open loop needs headroom beyond the closed-loop worker count:
    # with only `concurrency` workers, arrivals silently throttle to
    # the completion rate the moment latency exceeds the inter-arrival
    # gap — coordinated omission, the exact bias open loop exists to
    # avoid.  Workers auto-size (concurrency stays a floor) and any
    # residual schedule slip is MEASURED and banked, never hidden.
    n_workers = (max(1, concurrency) if mode != "open"
                 else min(requests, max(concurrency, 64)))
    t_start = time.perf_counter()

    def one(idx: int) -> None:
        if mode == "open" and rate > 0:
            # fixed arrival schedule: request idx fires at idx/rate
            # seconds after start, whatever the completions are doing
            delay = t_start + idx / rate - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            else:
                with rec_lock:
                    slips_ms.append(-delay * 1e3)
        img = gen_image(seed, idx, size_list)
        try:
            resp = post_predict(url, img, timeout=timeout)
        except Exception as e:  # noqa: BLE001 — recorded, not fatal
            with rec_lock:
                errors.append(f"req {idx}: {e!r}")
            return
        with rec_lock:
            records.append({
                "idx": idx,
                "t_wall": time.time(),
                "total_ms": resp["_latency_ms"],
                "phases": {k: resp.get("timings_ms", {}).get(k)
                           for k in PHASES},
                "bucket": resp.get("bucket"),
                "batch_fill": resp.get("batch_fill"),
                "batch_rung": resp.get("batch_rung"),
                "detections": len(resp.get("detections", ())),
                # checkpoint that served this request — the hot-reload
                # chaos rung joins these against the serve_reload
                # flight event to prove the flip boundary
                "params_step": resp.get("params_step"),
            })

    def worker() -> None:
        while True:
            try:
                idx = work.get_nowait()
            except queue.Empty:
                return
            one(idx)

    threads = [threading.Thread(target=worker, daemon=True,
                                name=f"loadgen-{i}")
               for i in range(n_workers)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t_start

    lat = [r["total_ms"] for r in records]
    phase_ms = {}
    for ph in PHASES:
        vals = [r["phases"][ph] for r in records
                if isinstance(r["phases"].get(ph), (int, float))]
        phase_ms[ph] = {"mean": round(float(np.mean(vals)), 3)
                        if vals else None,
                        "p99": round(_pct(vals, 99), 3)
                        if vals else None}
    fills = [r["batch_fill"] / r["batch_rung"] for r in records
             if r.get("batch_rung")]
    slowest = sorted(records, key=lambda r: -r["total_ms"])[:5]
    for s in slowest:
        ph = {k: v for k, v in s["phases"].items()
              if isinstance(v, (int, float))}
        s["dominant_phase"] = (max(ph, key=ph.get) if ph else None)
    open_loop = None
    if mode == "open":
        behind = [s for s in slips_ms if s > 5.0]
        open_loop = {
            "workers": n_workers,
            "arrivals_behind": len(behind),
            "slip_ms": {
                "mean": round(float(np.mean(slips_ms)), 3)
                if slips_ms else 0.0,
                "p99": round(_pct(slips_ms, 99), 3)
                if slips_ms else 0.0,
                "max": round(max(slips_ms), 3) if slips_ms else 0.0,
            },
            # nonzero arrivals_behind = the offered rate was NOT
            # fully sustained (worker pool or client box saturated);
            # the latency numbers then understate the true open-loop
            # tail — read them as a lower bound
            "offered_rate_sustained": not behind,
        }
    return {
        "kind": "serve_loadtest",
        "mode": mode,
        "rate_rps": rate if mode == "open" else None,
        "open_loop": open_loop,
        "requests": requests,
        "completed": len(records),
        "errors": len(errors),
        "error_samples": errors[:5],
        "concurrency": concurrency,
        "sizes": sizes,
        "seed": seed,
        "wall_s": round(wall_s, 3),
        "images_per_sec": round(len(records) / wall_s, 3)
        if wall_s > 0 else 0.0,
        "latency_ms": {
            "p50": round(_pct(lat, 50), 3),
            "p90": round(_pct(lat, 90), 3),
            "p99": round(_pct(lat, 99), 3),
            "mean": round(float(np.mean(lat)), 3) if lat else 0.0,
            "max": round(max(lat), 3) if lat else 0.0,
        },
        "phase_ms": phase_ms,
        "batch_occupancy_mean": round(float(np.mean(fills)), 3)
        if fills else None,
        "slowest": slowest,
        **({"records": records} if keep_records else {}),
    }


def build_bank(seed: int, sizes: str, requests: int) -> Dict:
    """The recorded request distribution in regenerable form: seed +
    per-request shapes, not pixel payloads — the bank stays kilobytes
    and ``gen_image(seed, idx, [(h, w)])`` reproduces every image
    bit-exactly at replay time."""
    size_list = [tuple(int(d) for d in s.split("x"))
                 for s in sizes.split(",") if s]
    return {
        "kind": "serve_request_bank",
        "seed": int(seed),
        "sizes": sizes,
        "requests": [
            {"idx": i,
             "h": size_list[i % len(size_list)][0],
             "w": size_list[i % len(size_list)][1]}
            for i in range(requests)],
        "recorded_at": _utcnow(),
    }


def bank_image(bank: Dict, row: Dict) -> np.ndarray:
    """Regenerate one banked request's image bit-exactly."""
    return gen_image(int(bank["seed"]), int(row["idx"]),
                     [(int(row["h"]), int(row["w"]))])


def detection_drift(a: Dict, b: Dict) -> float:
    """Output disagreement between two responses for ONE request,
    in [0, 1]; exactly 0.0 when the params are identical.

    Primary signal: the pre-threshold ``raw_top`` head outputs — per
    rank, a class disagreement counts 1.0 and a class match counts
    the score delta.  This stays nonzero for different params even
    when both checkpoints emit zero above-threshold detections (the
    degenerate case where a detections-based metric would
    silently report "no drift" between arbitrary params).  Fallback
    (no ``raw_top`` in the responses): greedy same-class IoU >= 0.5
    matching over the thresholded detections, drift = 1 - 2m/(na+nb).
    """
    ra, rb = a.get("raw_top"), b.get("raw_top")
    if ra and rb:
        k = min(len(ra["scores"]), len(rb["scores"]))
        if k == 0:
            return 0.0
        per_rank = [
            1.0 if ra["classes"][i] != rb["classes"][i]
            else min(1.0, abs(float(ra["scores"][i])
                              - float(rb["scores"][i])))
            for i in range(k)]
        return float(np.mean(per_rank))
    da, db = a.get("detections", []), b.get("detections", [])
    if not da and not db:
        return 0.0

    def iou(b1, b2) -> float:
        x0 = max(b1[0], b2[0]); y0 = max(b1[1], b2[1])  # noqa: E702
        x1 = min(b1[2], b2[2]); y1 = min(b1[3], b2[3])  # noqa: E702
        inter = max(0.0, x1 - x0) * max(0.0, y1 - y0)
        a1 = (b1[2] - b1[0]) * (b1[3] - b1[1])
        a2 = (b2[2] - b2[0]) * (b2[3] - b2[1])
        return inter / max(a1 + a2 - inter, 1e-9)

    unmatched = list(range(len(db)))
    matches = 0
    for d in da:
        best, best_iou = None, 0.5
        for j in unmatched:
            if d["class_id"] != db[j]["class_id"]:
                continue
            v = iou(d["box"], db[j]["box"])
            if v >= best_iou:
                best, best_iou = j, v
        if best is not None:
            unmatched.remove(best)
            matches += 1
    return 1.0 - 2.0 * matches / (len(da) + len(db))


def replay_shadow(bank: Dict, url: str, canary_url: str,
                  timeout: float = 120.0, raw_topk: int = 16,
                  score_thresh: Optional[float] = None,
                  concurrency: int = 4) -> Dict:
    """Mirror the banked traffic at incumbent AND canary; score the
    canary on latency p99 ratio, error rate, and output drift.

    Each worker sends one request to both servers back-to-back (the
    pair sees the same queue conditions, so the p99 ratio compares
    like with like), then diffs the outputs.  The score dict is what
    ``promotion_verdict`` (tools/eksml_operator.py) gates on."""
    rows = bank["requests"]
    rec_lock = threading.Lock()
    inc_lat: List[float] = []
    can_lat: List[float] = []
    drifts: List[float] = []
    inc_errors: List[str] = []
    can_errors: List[str] = []
    inc_steps: set = set()
    can_steps: set = set()
    work: "queue.Queue" = queue.Queue()
    for row in rows:
        work.put(row)

    def one(row: Dict) -> None:
        img = bank_image(bank, row)
        try:
            a = post_predict(url, img, timeout=timeout,
                             score_thresh=score_thresh,
                             raw_topk=raw_topk)
        except Exception as e:  # noqa: BLE001 — scored, not fatal
            with rec_lock:
                inc_errors.append(f"req {row['idx']}: {e!r}")
            return
        try:
            b = post_predict(canary_url, img, timeout=timeout,
                             score_thresh=score_thresh,
                             raw_topk=raw_topk)
        except Exception as e:  # noqa: BLE001 — scored, not fatal
            with rec_lock:
                inc_lat.append(a["_latency_ms"])
                can_errors.append(f"req {row['idx']}: {e!r}")
            return
        d = detection_drift(a, b)
        with rec_lock:
            inc_lat.append(a["_latency_ms"])
            can_lat.append(b["_latency_ms"])
            drifts.append(d)
            inc_steps.add(a.get("params_step"))
            can_steps.add(b.get("params_step"))

    def worker() -> None:
        while True:
            try:
                row = work.get_nowait()
            except queue.Empty:
                return
            one(row)

    threads = [threading.Thread(target=worker, daemon=True,
                                name=f"shadow-{i}")
               for i in range(max(1, concurrency))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    inc_p99, can_p99 = _pct(inc_lat, 99), _pct(can_lat, 99)
    scored = len(drifts)
    return {
        "kind": "serve_shadow_score",
        "bank_seed": bank.get("seed"),
        "requests": len(rows),
        "scored": scored,
        "incumbent": {
            "url": url,
            "errors": len(inc_errors),
            "error_samples": inc_errors[:3],
            "params_steps": sorted(
                s for s in inc_steps if s is not None),
            "latency_ms": {"p50": round(_pct(inc_lat, 50), 3),
                           "p99": round(inc_p99, 3)},
        },
        "canary": {
            "url": canary_url,
            "errors": len(can_errors),
            "error_samples": can_errors[:3],
            "params_steps": sorted(
                s for s in can_steps if s is not None),
            "latency_ms": {"p50": round(_pct(can_lat, 50), 3),
                           "p99": round(can_p99, 3)},
        },
        # the three gate axes (promotion_verdict reads exactly these)
        "p99_ratio": round(can_p99 / inc_p99, 4) if inc_p99 > 0
        else None,
        "canary_error_rate": round(
            len(can_errors) / max(len(rows), 1), 4),
        "drift": {
            "mean": round(float(np.mean(drifts)), 6) if drifts else None,
            "p99": round(_pct(drifts, 99), 6) if drifts else None,
            "max": round(max(drifts), 6) if drifts else None,
        },
        "scored_at": _utcnow(),
    }


def next_bank_path(artifacts_dir: str, prefix: str = "serve") -> str:
    """First free ``<prefix>_r<N>.json`` slot."""
    taken = set()
    for p in glob.glob(os.path.join(artifacts_dir,
                                    f"{prefix}_r*.json")):
        m = re.match(prefix + r"_r(\d+)\.json$", os.path.basename(p))
        if m:
            taken.add(int(m.group(1)))
    n = 1
    while n in taken:
        n += 1
    return os.path.join(artifacts_dir, f"{prefix}_r{n}.json")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--url", default=None,
                   help="server base URL, e.g. http://127.0.0.1:8081")
    p.add_argument("--port-file", default=None,
                   help="read the port from this file (the --port-file "
                        "the server wrote) and target 127.0.0.1")
    p.add_argument("--requests", type=int, default=200)
    p.add_argument("--concurrency", type=int, default=8)
    p.add_argument("--mode", choices=["closed", "open"],
                   default="closed")
    p.add_argument("--rate", type=float, default=0.0,
                   help="open-loop arrival rate (requests/sec)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--sizes", default=DEFAULT_SIZES,
                   help="comma list of HxW request image sizes "
                        "[%(default)s]")
    p.add_argument("--timeout", type=float, default=120.0)
    p.add_argument("--wait-ready", type=float, default=600.0,
                   help="seconds to wait for /healthz ok before load")
    p.add_argument("--out", default=None,
                   help="write the artifact here (atomic)")
    p.add_argument("--bank", action="store_true",
                   help="write to the next free "
                        "artifacts/serve_r<N>.json slot")
    p.add_argument("--note", default=None,
                   help="free-text provenance recorded in the "
                        "artifact (geometry, hardware, caveats)")
    p.add_argument("--record", default=None, metavar="PATH",
                   help="bank the request distribution (seed + "
                        "shapes) here and exit — no server needed")
    p.add_argument("--replay", default=None, metavar="BANK",
                   help="replay a recorded bank instead of generating "
                        "fresh traffic")
    p.add_argument("--shadow", action="store_true",
                   help="with --replay: mirror each request at "
                        "--canary-url too and score the canary "
                        "(latency p99 ratio, error rate, drift)")
    p.add_argument("--canary-url", default=None,
                   help="canary base URL for --shadow scoring")
    p.add_argument("--raw-topk", type=int, default=16,
                   help="pre-threshold top-k raw outputs per request "
                        "for the drift signal [%(default)s]")
    args = p.parse_args(argv)

    if args.record:
        bank = build_bank(args.seed, args.sizes, args.requests)
        os.makedirs(os.path.dirname(args.record) or ".", exist_ok=True)
        atomic_write_json(args.record, bank)
        print(f"recorded {len(bank['requests'])} request(s) -> "
              f"{args.record}")
        return 0

    if args.url:
        url = args.url
    elif args.port_file:
        deadline = time.monotonic() + args.wait_ready
        while not os.path.exists(args.port_file):
            if time.monotonic() > deadline:
                p.error(f"port file {args.port_file} never appeared")
            time.sleep(0.2)
        url = f"http://127.0.0.1:{open(args.port_file).read().strip()}"
    else:
        p.error("need --url or --port-file")
    if args.mode == "open" and args.rate <= 0:
        p.error("--mode open needs --rate > 0")

    if args.shadow:
        if not (args.replay and args.canary_url):
            p.error("--shadow needs --replay BANK and --canary-url")
        with open(args.replay) as f:
            bank = json.load(f)
        wait_ready(url, budget=args.wait_ready)
        wait_ready(args.canary_url, budget=args.wait_ready)
        score = replay_shadow(bank, url, args.canary_url,
                              timeout=args.timeout,
                              raw_topk=args.raw_topk,
                              concurrency=args.concurrency)
        if args.note:
            score["note"] = args.note
        print(json.dumps(score, indent=1))
        out = args.out
        if out is None and args.bank:
            out = next_bank_path(os.path.join(REPO, "artifacts"),
                                 prefix="shadow")
        if out:
            os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
            atomic_write_json(out, score)
            print(f"banked {out}", file=sys.stderr)
        return 0 if score["canary_error_rate"] == 0 else 1

    if args.replay:
        # a bank IS (seed, sizes, count) — replaying without --shadow
        # is run_load over the exact recorded distribution
        with open(args.replay) as f:
            bank = json.load(f)
        args.seed = int(bank["seed"])
        args.sizes = bank["sizes"]
        args.requests = len(bank["requests"])

    health = wait_ready(url, budget=args.wait_ready)
    artifact = run_load(url, args.requests, args.concurrency,
                        mode=args.mode, rate=args.rate, seed=args.seed,
                        sizes=args.sizes, timeout=args.timeout)
    # post-run engine state: the zero-cold-compile proof and the
    # per-chip normalization ride the SAME scrape the HPA uses
    try:
        post = fetch_health(url)
        metrics = scrape_metrics(url)
    except (urllib.error.URLError, OSError) as e:
        post, metrics = {"error": repr(e)}, ""
    devices = int(post.get("devices") or health.get("devices") or 1)
    artifact.update({
        "url": url,
        "devices": devices,
        "images_per_sec_per_chip": round(
            artifact["images_per_sec"] / max(devices, 1), 3),
        "engine": {
            "compiles": post.get("compiles"),
            "request_path_compiles": post.get("request_path_compiles"),
            "warm_executables": post.get("warm_executables"),
            "buckets": post.get("buckets"),
            "batch_rungs": post.get("batch_rungs"),
        },
        "zero_request_path_compiles":
            post.get("request_path_compiles") == 0,
        "metrics": {
            "requests_ok": metric_value(
                metrics, "eksml_serve_requests_total",
                '{outcome="ok"}'),
            "batches": metric_value(metrics,
                                    "eksml_serve_batches_total"),
            "aot_compiles": metric_value(
                metrics, "eksml_serve_aot_compiles_total"),
            "request_path_compiles": metric_value(
                metrics, "eksml_serve_request_path_compiles_total"),
        },
        "banked_at": _utcnow(),
    })
    if args.note:
        artifact["note"] = args.note
    payload = json.dumps(artifact, indent=1)
    print(payload)
    out = args.out
    if out is None and args.bank:
        out = next_bank_path(os.path.join(REPO, "artifacts"))
    if out:
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        atomic_write_json(out, artifact)
        print(f"banked {out}", file=sys.stderr)
    return 0 if artifact["errors"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
