#!/bin/bash
# One healthy tunnel window must bank EVERY hardware artifact
# (VERDICT r2: the 3.4x Pallas claim died as prose because nothing was
# committed in the window that measured it; VERDICT r3: the one healthy
# window died compiling the most expensive point first).  This script
# waits for the patient retry loop's headline success (BENCH_LOCAL.json
# — itself now a cheap-first ladder), then harvests in STRICT
# cheap-first order: the 512px A/B trio, the hardware convergence run,
# then the expensive A/B pairs and a profiled run — banking each result
# into artifacts/ as it lands.  Tunnel discipline throughout: clients
# are never killed; every run waits for any other bench to finish first.
set -u
cd "$(dirname "$0")/.."
LOG=tpu_harvest.log
WAIT_HEADLINE=${WAIT_HEADLINE:-1}

say() { echo "[harvest] $(date -u +%H:%M:%S) $*" >> "$LOG"; }

wait_for_bench_slot() {
    # one TPU client at a time: bench.py AND any TPU convergence run
    # count as holding the slot.  A `--platform cpu` convergence hedge
    # (run in parallel on the host) does NOT hold the TPU.
    while pgrep -af "python bench.py|tools/convergence_run.py" \
        2>/dev/null | grep -v "platform cpu" | grep -q .; do
        sleep 60
    done
}

run_bench() {  # run_bench <tag> <args...> -> writes artifacts/<tag>.json
    local tag=$1; shift
    # skip only artifacts that are clean AND from real hardware — a
    # CPU-fallback success must not block the hardware measurement
    if python -c '
import json, sys
try:
    d = json.load(open("artifacts/" + sys.argv[1] + ".json"))
except Exception:
    sys.exit(1)
ok = "error" not in d and d.get("value", 0) > 0 and \
    d.get("device_kind", "").lower() not in ("", "cpu", "host")
sys.exit(0 if ok else 1)' "$tag" 2>/dev/null; then
        say "skip $tag: already banked clean on hardware"
        return 0
    fi
    wait_for_bench_slot
    say "run $tag: bench.py --single $*"
    python bench.py --single "$@" --init-retries 3 --init-timeout 300 \
        2>>"$LOG" | tail -1 > "artifacts/$tag.json.tmp" \
        && mv "artifacts/$tag.json.tmp" "artifacts/$tag.json"
    say "done $tag: $(head -c 200 "artifacts/$tag.json")"
}

merge_ab() {
    python - <<'EOF'
import json, glob
out = []
import re
for p in sorted(glob.glob("artifacts/roi_ab_*.json")):
    if re.search(r"roi_ab_r\d+\.json$", p):  # merged outputs (any round)
        continue
    try:
        d = json.load(open(p))
    except Exception:
        continue
    out.append({"run": p.split("/")[-1][:-5], **{k: d.get(k) for k in (
        "value", "step_time_ms", "mfu", "roi_backend", "roi_bwd",
        "image_size", "batch_size", "device_kind", "error")}})
json.dump({"runs": out}, open("artifacts/roi_ab_r5.json", "w"), indent=1)
print("merged", len(out), "runs into artifacts/roi_ab_r5.json")
EOF
}

run_convergence() {
    # Convergence at real model scale ON HARDWARE (VERDICT r3 next #4):
    # the full R50-FPN run that takes most of a day on the 1-core CPU
    # box finishes in minutes on the chip.  Gate: run only while no
    # banked r5 artifact already shows a non-CPU run beating the r3
    # CPU-hedge AP50 (0.5284); promote only a real-accelerator run that
    # does not regress it.  Banked to a separate file first so a
    # half-written artifact can never clobber a good one.
    if python -c '
import json, sys
try:
    d = json.load(open("artifacts/convergence_r5.json"))
except Exception:
    sys.exit(0)  # nothing banked: run
ok = d.get("device", "cpu").lower() not in ("", "cpu", "host") \
    and d.get("bbox_AP50", 0) > 0.53
sys.exit(1 if ok else 0)
'; then
        wait_for_bench_slot
        # BACKBONE.NORM=GN: the real ladder warm-starts FreezeBN from
        # the ImageNet npz; with no egress the backbone trains from
        # scratch, and FreezeBN at random init cannot normalize — GN is
        # the architecture's supported from-scratch norm (round 3).
        say "running TPU convergence (full R50-FPN, 512px, GN)"
        if python tools/convergence_run.py --steps 600 --size 512 \
            --batch-size 4 \
            --out artifacts/convergence_r5_tpu.json \
            --config RPN.TRAIN_PRE_NMS_TOPK=512 RPN.TRAIN_POST_NMS_TOPK=128 \
            RPN.TEST_PRE_NMS_TOPK=512 RPN.TEST_POST_NMS_TOPK=128 \
            FRCNN.BATCH_PER_IM=128 TRAIN.GRADIENT_CLIP=0.36 \
            BACKBONE.NORM=GN \
            >> "$LOG" 2>&1; then
            if reason=$(python -c '
import json, sys
d = json.load(open("artifacts/convergence_r5_tpu.json"))
if d.get("device", "").lower() in ("", "cpu", "host"):
    print("ran on CPU fallback"); sys.exit(1)
try:
    old = json.load(open("artifacts/convergence_r3.json"))
except Exception:
    sys.exit(0)
if d.get("bbox_AP50", 0) < old.get("bbox_AP50", 0):
    print("AP50 %.3f below banked %.3f" % (
        d.get("bbox_AP50", 0), old.get("bbox_AP50", 0)))
    sys.exit(1)
'); then
                cp artifacts/convergence_r5_tpu.json \
                   artifacts/convergence_r5.json
                say "TPU convergence banked as convergence_r5.json"
            else
                say "TPU convergence NOT promoted: $reason"
            fi
        else
            say "TPU convergence run FAILED its own checks (see log)"
        fi
    else
        say "convergence_r5.json already strong on hardware; skipping"
    fi
}

# same stale-headline guard as the supervisor (code review r5): a
# leftover BENCH_LOCAL.json from a prior round must not unleash the
# harvest chain — an unstamped or >2h-old copy is set aside (renamed,
# not deleted).  The wait below then resumes: with BENCH_LOCAL gone the
# supervisor keeps the retry loop hunting, and the warm compile cache
# makes a re-landing cheap.
if [ -e BENCH_LOCAL.json ]; then
    python tools/bench_local_util.py rotate 2>/dev/null || true
    [ -e BENCH_LOCAL.json ] || say "set aside stale BENCH_LOCAL.json"
fi

if [ "$WAIT_HEADLINE" = "1" ]; then
    say "waiting for BENCH_LOCAL.json (ladder via bench_retry_loop)"
    while [ ! -s BENCH_LOCAL.json ]; do sleep 120; done
    say "headline landed: $(head -c 200 BENCH_LOCAL.json)"
fi

# ---- Rung 1 (cheap, lands in minutes): 512px A/B trio -------------
# fwd A/B pins --roi-bwd xla so the forward kernel is the ONLY
# variable; the bwd run then varies only the backward.
run_bench roi_ab_pallas_512 --steps 10 --image-size 512 \
    --roi-backend pallas --roi-bwd xla
run_bench roi_ab_xla_512 --steps 10 --image-size 512 \
    --roi-backend xla --roi-bwd xla
run_bench roi_ab_bwd_pallas_512 --steps 10 --image-size 512 \
    --roi-backend pallas --roi-bwd pallas
merge_ab
say "cheap A/B trio merged"

# ---- Rung 2: hardware convergence (minutes on-chip) ----------------
run_convergence

# ---- Rung 3: production-shape A/B pairs ----------------------------
run_bench roi_ab_pallas_832x1344 --steps 10 --roi-backend pallas \
    --roi-bwd xla --pad-hw 832 1344
run_bench roi_ab_xla_832x1344 --steps 10 --roi-backend xla \
    --roi-bwd xla --pad-hw 832 1344
run_bench roi_ab_pallas_1344 --steps 10 --roi-backend pallas --roi-bwd xla
run_bench roi_ab_xla_1344 --steps 10 --roi-backend xla --roi-bwd xla
run_bench roi_ab_bwd_pallas_1344 --steps 10 --roi-backend pallas \
    --roi-bwd pallas
merge_ab
say "full A/B grid merged into artifacts/roi_ab_r5.json"

# ---- Rung 4: train-step profile (go/no-go on a real trace) ---------
run_bench bench_profiled --steps 10 --profile 8
if python tools/trace_summary.py profile \
    --out artifacts/profile_summary_r5.json >> "$LOG" 2>&1; then
    say "profile summary banked"
else
    say "profile summary FAILED — see above; trace left in ./profile"
fi
# ---- Rung 5: headline retry if the banked ladder stopped short ----
# Every A/B compile above warmed .jax_cache, so a full ladder rerun is
# mostly dispatch; only upgrade BENCH_LOCAL when the 1344/b4 point
# actually landed on hardware.
if ! python -c '
import json, sys
d = json.load(open("BENCH_LOCAL.json"))
sys.exit(0 if d.get("headline_point") else 1)' 2>/dev/null; then
    wait_for_bench_slot
    say "retrying full ladder for the headline point"
    # tmp+mv atomic write, same as run_bench (ADVICE r4): a harvest
    # killed mid-write must not leave a truncated artifact
    python bench.py --steps 20 --init-retries 3 --init-timeout 300 \
        2>>"$LOG" | tail -1 > artifacts/bench_ladder_retry.json.tmp \
        && mv artifacts/bench_ladder_retry.json.tmp \
              artifacts/bench_ladder_retry.json
    if python -c '
import json, sys
d = json.load(open("artifacts/bench_ladder_retry.json"))
ok = d.get("value", 0) > 0 and d.get("headline_point") and \
    d.get("device_kind", "").lower() not in ("", "cpu", "host")
sys.exit(0 if ok else 1)'; then
        # stamp banked_at (same contract as the loop's write): an
        # unstamped BENCH_LOCAL fails bank_round's --since filter and
        # the supervisor/harvest stale checks (code review r5)
        if python tools/bench_local_util.py stamp \
            --out BENCH_LOCAL.json \
            --from-file artifacts/bench_ladder_retry.json; then
            say "headline point upgraded into BENCH_LOCAL.json"
        else
            say "STAMP FAILED; keeping banked ladder result"
        fi
    else
        say "headline retry did not land; keeping banked ladder result"
    fi
fi
say "harvest complete"
