#!/bin/bash
# One healthy tunnel window must bank EVERY hardware artifact
# (VERDICT r2: the 3.4x Pallas claim died as prose because nothing was
# committed in the window that measured it).  This script waits for the
# patient retry loop's headline success (BENCH_LOCAL.json), then runs
# the ROIAlign A/B grid and a profiled run, banking each result into
# artifacts/ as it lands.  Tunnel discipline throughout: clients are
# never killed; every run waits for any other bench to finish first.
set -u
cd "$(dirname "$0")/.."
LOG=tpu_harvest.log
WAIT_HEADLINE=${WAIT_HEADLINE:-1}

say() { echo "[harvest] $(date -u +%H:%M:%S) $*" >> "$LOG"; }

wait_for_bench_slot() {
    # one TPU client at a time: bench.py AND any TPU convergence run
    # count as holding the slot.  A `--platform cpu` convergence hedge
    # (run in parallel on the host) does NOT hold the TPU.
    while pgrep -af "python bench.py|tools/convergence_run.py" \
        2>/dev/null | grep -v "platform cpu" | grep -q .; do
        sleep 60
    done
}

run_bench() {  # run_bench <tag> <args...> -> writes artifacts/<tag>.json
    local tag=$1; shift
    wait_for_bench_slot
    say "run $tag: bench.py $*"
    python bench.py "$@" --init-retries 3 --init-timeout 300 \
        2>>"$LOG" | tail -1 > "artifacts/$tag.json"
    say "done $tag: $(head -c 200 "artifacts/$tag.json")"
}

if [ "$WAIT_HEADLINE" = "1" ]; then
    say "waiting for BENCH_LOCAL.json (headline via bench_retry_loop)"
    while [ ! -s BENCH_LOCAL.json ]; do sleep 120; done
    say "headline landed: $(head -c 200 BENCH_LOCAL.json)"
fi

# ROIAlign A/B on hardware (VERDICT r2 next #2): square canvas and the
# 832x1344 bucket canvas, pallas vs xla, plus the backward-kernel A/B
# (pallas fwd fixed, bwd pallas vs xla).  Short runs; the compile for
# each variant is paid once into .jax_cache.
# fwd A/B pins --roi-bwd xla so the forward kernel is the ONLY
# variable; the bwd pair then varies only the backward
run_bench roi_ab_pallas_1344   --steps 10 --roi-backend pallas --roi-bwd xla
run_bench roi_ab_xla_1344      --steps 10 --roi-backend xla --roi-bwd xla
run_bench roi_ab_pallas_832x1344 --steps 10 --roi-backend pallas --roi-bwd xla --pad-hw 832 1344
run_bench roi_ab_xla_832x1344  --steps 10 --roi-backend xla --roi-bwd xla --pad-hw 832 1344
# bwd A/B: compare against roi_ab_pallas_1344 (pallas fwd + xla bwd)
run_bench roi_ab_bwd_pallas_1344 --steps 10 --roi-backend pallas --roi-bwd pallas
python - <<'EOF'
import json, glob
out = []
for p in sorted(glob.glob("artifacts/roi_ab_*.json")):
    if p.endswith("roi_ab_r3.json"):  # the merged output itself
        continue
    try:
        d = json.load(open(p))
    except Exception:
        continue
    out.append({"run": p.split("/")[-1][:-5], **{k: d.get(k) for k in (
        "value", "step_time_ms", "mfu", "roi_backend", "roi_bwd",
        "image_size", "error")}})
json.dump({"runs": out}, open("artifacts/roi_ab_r3.json", "w"), indent=1)
print("merged", len(out), "runs into artifacts/roi_ab_r3.json")
EOF
say "A/B merged into artifacts/roi_ab_r3.json"

# Train-step profile (VERDICT r2 next #5): decide the Pallas-backward
# go/no-go on a real trace.
run_bench bench_profiled --steps 10 --profile 8
if python tools/trace_summary.py profile \
    --out artifacts/profile_summary_r3.json >> "$LOG" 2>&1; then
    say "profile summary banked"
else
    say "profile summary FAILED — see above; trace left in ./profile"
fi

# Convergence at real model scale ON HARDWARE (VERDICT r2 next #4):
# the full R50-FPN run that takes most of a day on the 1-core CPU box
# finishes in minutes on the chip.  One AP-based gate: run only while
# no banked artifact shows strong convergence (bbox AP50 >= 0.5 — the
# convergence FACT is then proven and the slot is better spent on the
# headline/A-B/profile); promote only a real-accelerator run that does
# not regress the banked AP50.  Banked to a separate file first so a
# half-written artifact can never clobber a good one.
if python -c '
import json, sys
try:
    d = json.load(open("artifacts/convergence_r3.json"))
except Exception:
    sys.exit(0)  # nothing banked: run
sys.exit(1 if d.get("bbox_AP50", 0) >= 0.5 else 0)
'; then
    wait_for_bench_slot
    # BACKBONE.NORM=GN: the real ladder warm-starts FreezeBN from the
    # ImageNet npz; with no egress the backbone trains from scratch,
    # and FreezeBN at random init (unit stats, never updated) cannot
    # normalize — the round-3 CPU hedge plateaued exactly this way.
    # GroupNorm is the architecture's supported from-scratch norm.
    say "running TPU convergence (full R50-FPN, 512px, GN)"
    if python tools/convergence_run.py --steps 500 --size 512 \
        --batch-size 4 \
        --out artifacts/convergence_r3_tpu.json \
        --config RPN.TRAIN_PRE_NMS_TOPK=512 RPN.TRAIN_POST_NMS_TOPK=128 \
        RPN.TEST_PRE_NMS_TOPK=512 RPN.TEST_POST_NMS_TOPK=128 \
        FRCNN.BATCH_PER_IM=128 TRAIN.GRADIENT_CLIP=0.36 \
        BACKBONE.NORM=GN \
        >> "$LOG" 2>&1; then
        if reason=$(python -c '
import json, sys
d = json.load(open("artifacts/convergence_r3_tpu.json"))
if d.get("device", "").lower() in ("", "cpu", "host"):
    print("ran on CPU fallback"); sys.exit(1)
try:
    old = json.load(open("artifacts/convergence_r3.json"))
except Exception:
    sys.exit(0)
if d.get("bbox_AP50", 0) < old.get("bbox_AP50", 0):
    print("AP50 %.3f below banked %.3f" % (
        d.get("bbox_AP50", 0), old.get("bbox_AP50", 0)))
    sys.exit(1)
'); then
            cp artifacts/convergence_r3_tpu.json \
               artifacts/convergence_r3.json
            say "TPU convergence banked as convergence_r3.json"
        else
            say "TPU convergence NOT promoted: $reason"
        fi
    else
        say "TPU convergence run FAILED its own checks (see log)"
    fi
else
    say "convergence_r3.json already strong (AP50>=0.5); skipping"
fi
say "harvest complete"
