#!/bin/bash
# Round-5b harvest: bank the evidence for THIS session's perf work
# (tiled NMS, stacked per-level NMS, Pallas-bwd async write-back) the
# moment a healthy tunnel window lands the fresh headline ladder.
#
# Order (cheap/decisive first, same tunnel discipline as tpu_harvest.sh
# — never kill a client mid-compile, one TPU client at a time):
#   1. bwd-overlap A/B at the 1344/b4 headline (EKSML_BWD_OVERLAP=0/1;
#      the tiled-NMS delta is read against the git-banked r5 rungs,
#      which ran the same flags on the same chip)
#   2. long hardware convergence (2500 steps) — promoted to
#      convergence_r5.json only if bbox AP50 beats the r3 CPU bar
#   3. fresh profiled run + trace summary (was the NMS phase actually
#      cut?)
#
# The ONE deviation from "never kill": a convergence client that has
# written ZERO training steps for 35 minutes is dead (today's observed
# failure: backend init hung while the tunnel port stayed open; the
# process held the slot for 50 min with zero IO).  Zero-step kills
# cannot be mid-compile-cache-write: the persistent cache commits per
# XLA module, and a client that never stepped never held a partially
# compiled train step worth preserving.
set -u
cd "$(dirname "$0")/.."
LOG=tpu_harvest_r5b.log

say() { echo "[r5b] $(date -u +%H:%M:%S) $*" >> "$LOG"; }

wait_slot() {
    while pgrep -af "python bench.py|tools/convergence_run.py" \
        2>/dev/null | grep -v "platform cpu" | grep -q .; do
        sleep 60
    done
}

run_single() {  # run_single <tag> <extra env...> -- <bench args...>
    local tag=$1; shift
    local envs=()
    while [ "$1" != "--" ]; do envs+=("$1"); shift; done
    shift
    wait_slot
    say "run $tag: ${envs[*]:-} bench.py --single $*"
    env "${envs[@]}" python bench.py --single "$@" \
        --init-retries 3 --init-timeout 300 \
        2>>"$LOG" | tail -1 > "artifacts/$tag.json.tmp"
    # promote only non-empty parseable JSON: the pipeline exits with
    # tail's status, so a crashed bench would otherwise bank an empty
    # artifact and log "done" (code review r5)
    if python -c "import json,sys; json.load(open(sys.argv[1]))" \
        "artifacts/$tag.json.tmp" 2>/dev/null; then
        mv "artifacts/$tag.json.tmp" "artifacts/$tag.json"
        say "done $tag: $(head -c 200 "artifacts/$tag.json")"
    else
        rm -f "artifacts/$tag.json.tmp"
        say "FAILED $tag: bench produced no JSON (see $LOG)"
    fi
}

say "waiting for fresh headline (BENCH_LOCAL.json)"
while [ ! -s BENCH_LOCAL.json ]; do sleep 120; done
say "headline landed: $(head -c 200 BENCH_LOCAL.json)"

# ---- 1. bwd async-write-back attribution at the headline point -----
run_single roi_ab_overlap_off_1344 EKSML_BWD_OVERLAP=0 -- \
    --steps 10 --image-size 1344 --batch-size 4 \
    --roi-backend pallas --roi-bwd pallas
run_single roi_ab_overlap_on_1344 EKSML_BWD_OVERLAP=1 -- \
    --steps 10 --image-size 1344 --batch-size 4 \
    --roi-backend pallas --roi-bwd pallas
python - >> "$LOG" 2>&1 <<'EOF'
import json
rows = []
for tag in ("roi_ab_overlap_off_1344", "roi_ab_overlap_on_1344"):
    try:
        d = json.load(open(f"artifacts/{tag}.json"))
    except Exception:
        continue
    rows.append({"run": tag, **{k: d.get(k) for k in (
        "value", "step_time_ms", "mfu", "device_kind", "error")}})
json.dump({"runs": rows},
          open("artifacts/roi_ab_overlap_r5b.json", "w"), indent=1)
print("merged overlap A/B:", rows)
EOF
say "overlap A/B merged"

# ---- 2. long hardware convergence with a zero-progress watchdog ----
wait_slot
say "long TPU convergence: 2500 steps @512/b4"
# pre-create the dataset dir so the watchdog tracks THIS run's
# metrics file, not a stale /tmp/shapes_coco_* glob from an earlier
# (possibly hung) attempt (code review r5)
conv_dir=$(mktemp -d /tmp/shapes_coco_r5b.XXXXXX)
python - "$conv_dir" >> "$LOG" 2>&1 <<'EOF'
import sys
from tools.make_shapes_coco import make_split
base = sys.argv[1]
make_split(base, "train2017", 200, 512, 0, 1000)
make_split(base, "val2017", 30, 512, 1, 100000)
print("r5b dataset at", base)
EOF
conv_metrics="$conv_dir/run/metrics.jsonl"
python tools/convergence_run.py --steps 2500 --size 512 --batch-size 4 \
    --data "$conv_dir" \
    --out artifacts/convergence_r5_tpu_long.json \
    --config RPN.TRAIN_PRE_NMS_TOPK=512 RPN.TRAIN_POST_NMS_TOPK=128 \
    RPN.TEST_PRE_NMS_TOPK=512 RPN.TEST_POST_NMS_TOPK=128 \
    FRCNN.BATCH_PER_IM=128 TRAIN.GRADIENT_CLIP=0.36 BACKBONE.NORM=GN \
    >> "$LOG" 2>&1 &
conv_pid=$!
# watchdog: kill ONLY a zero-step client (see header); a stepping run
# is left alone no matter how slow
for _ in $(seq 35); do
    sleep 60
    kill -0 "$conv_pid" 2>/dev/null || break
    if [ -s "$conv_metrics" ]; then
        say "convergence stepping; watchdog standing down"
        break
    fi
done
if kill -0 "$conv_pid" 2>/dev/null && [ ! -s "$conv_metrics" ]; then
    say "convergence wrote ZERO steps in 35 min — killing hung client"
    kill "$conv_pid" 2>/dev/null
fi
wait "$conv_pid" 2>/dev/null
if reason=$(python -c '
import json, sys
try:
    d = json.load(open("artifacts/convergence_r5_tpu_long.json"))
except Exception:
    print("no artifact"); sys.exit(1)
if d.get("device", "").lower() in ("", "cpu", "host"):
    print("ran on CPU fallback"); sys.exit(1)
old = json.load(open("artifacts/convergence_r3.json"))
if d.get("bbox_AP50", 0) < old.get("bbox_AP50", 0):
    print("AP50 %.3f below r3 bar %.3f" % (
        d.get("bbox_AP50", 0), old.get("bbox_AP50", 0)))
    sys.exit(1)
'); then
    cp artifacts/convergence_r5_tpu_long.json artifacts/convergence_r5.json
    say "long convergence PROMOTED to convergence_r5.json"
else
    say "long convergence not promoted: $reason"
fi

# ---- 3. fresh profile: did the NMS/bwd phases actually shrink? -----
# drop any prior run's promoted artifact first: the freshness guard
# below reads it, and run_single only cleans up .tmp files on failure
rm -f artifacts/bench_profiled_r5b.json
run_single bench_profiled_r5b -- --steps 10 --image-size 1344 \
    --batch-size 4 --profile 8
# Summarize ONLY a trace this run produced: a failed profiled bench
# leaves the previous session's trace as the newest dir, and
# trace_summary would bank the OLD step under the fresh r5b label
# (observed 20:42 UTC — a stale-evidence hazard, deleted by hand).
if python - <<'EOF'
import json, sys
try:
    d = json.load(open("artifacts/bench_profiled_r5b.json"))
except Exception:
    sys.exit(1)
sys.exit(0 if (d.get("value") or 0) > 0 else 1)
EOF
then
    if python tools/trace_summary.py profile \
        --out artifacts/profile_summary_r5b.json >> "$LOG" 2>&1; then
        say "fresh profile summary banked"
    fi
else
    say "profiled bench failed; NOT summarizing the stale trace"
fi
say "r5b harvest complete"

# ---- 4. batch-8 headline probe: does a bigger batch lift MFU? ------
# HBM-OOM auto-retries once with remat inside bench.py (--single path
# included, bench.py:_run_with_remat); artifact is labeled by its own
# batch_size/remat fields either way.
run_single bench_1344_b8 -- --steps 10 --image-size 1344 --batch-size 8
say "r5b extended harvest complete"
