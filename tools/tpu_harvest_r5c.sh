#!/bin/bash
# Round-5c harvest: re-bank the headline evidence AFTER the vmem-limit
# fix (per-kernel compiler_params).  The 19:40 UTC ladder ran with the
# Pallas probe rejected at the compiler's un-overridable 16 MiB default
# — XLA-fallback numbers (5.62 img/s at 1344/b4) that under-report the
# framework by ~2x.  This script, run after the fix:
#   1. fresh full ladder (banks BENCH_LOCAL.json + bench_rung_*.json,
#      probe now passes → pallas fwd+bwd on) — also warms the compile
#      cache so later probes join within their 120s deadline
#   2. overlap A/B at the headline (EKSML_BWD_OVERLAP=0/1, forced
#      pallas) — the bwd async-write-back attribution, and the
#      hardware validation of the base+2x-extra overlap grant
#   3. long hardware convergence (2500 steps @512/b4) with
#      EKSML_PROBE_TIMEOUT=600: the 120s default expired mid-compile
#      on the cold f32 probe and the abandoned thread held the
#      tunnel's serialized compile slot (the r5b zero-step wedge)
# Same tunnel discipline as tpu_harvest_r5b.sh: one client at a time,
# never kill mid-compile, zero-step watchdog only.
set -u
cd "$(dirname "$0")/.."
LOG=tpu_harvest_r5c.log

say() { echo "[r5c] $(date -u +%H:%M:%S) $*" >> "$LOG"; }

wait_slot() {
    while pgrep -af "python bench.py|tools/convergence_run.py" \
        2>/dev/null | grep -v "platform cpu" | grep -q .; do
        sleep 60
    done
}

run_single() {  # run_single <tag> <extra env...> -- <bench args...>
    local tag=$1; shift
    local envs=()
    while [ "$1" != "--" ]; do envs+=("$1"); shift; done
    shift
    wait_slot
    wait_port
    say "run $tag: ${envs[*]:-} bench.py --single $*"
    env "${envs[@]}" python bench.py --single "$@" \
        --init-retries 3 --init-timeout 300 \
        2>>"$LOG" | tail -1 > "artifacts/$tag.json.tmp"
    if python -c "import json,sys; json.load(open(sys.argv[1]))" \
        "artifacts/$tag.json.tmp" 2>/dev/null; then
        mv "artifacts/$tag.json.tmp" "artifacts/$tag.json"
        say "done $tag: $(head -c 200 "artifacts/$tag.json")"
    else
        rm -f "artifacts/$tag.json.tmp"
        say "FAILED $tag: bench produced no JSON (see $LOG)"
    fi
}

# ---- 1. fresh post-fix ladder: retry until a pallas-on headline ----
# (roi_backend auto + probe pass => pallas; a ladder that lands with
# the probe STILL failing would bank roi=auto with the same 5.62-class
# value — detect via the banked rung's value and retry a bounded
# number of times)
wait_port() {
    # During a CLOSED-port window bench.py's pre-flight rejects in
    # milliseconds; without this wait each rejection would consume a
    # ladder attempt and the whole budget would burn in minutes.
    # Attempts are for REAL failures (init hang on an open port, bad
    # numbers) — port-closed time is free.  Logs once per ~10 min.
    local n=0
    while ! python - <<'EOF'
import socket, sys
try:
    socket.create_connection(("127.0.0.1", 8103), timeout=0.75).close()
except OSError:
    sys.exit(1)
EOF
    do
        n=$((n + 1))
        [ $((n % 20)) -eq 1 ] && say "tunnel port closed (x$n); waiting"
        sleep 30
    done
}

ladder_ok=""
for i in 1 2 3 4 5 6; do
    wait_slot
    wait_port
    say "ladder attempt $i"
    # EKSML_PROBE_TIMEOUT=600: the first post-wake probe compile is
    # COLD (the persistent cache only helps once a probe compile has
    # completed in some client) and routinely exceeds the 120s
    # default over the tunnel — which silently measures the XLA
    # fallback and burns the attempt on a 5.6-class number
    EKSML_PROBE_TIMEOUT=600 \
    python bench.py --steps 20 --init-retries 3 --init-timeout 300 \
        > .bench_r5c.tmp 2>>"$LOG"
    line=$(tail -1 .bench_r5c.tmp)
    ok=$(python - "$line" <<'EOF'
import json, sys
try:
    d = json.loads(sys.argv[1])
except Exception:
    print("parse"); raise SystemExit
hw = "tpu" in (d.get("device_kind") or "").lower()
# post-fix pallas headline should clear the banked XLA-fallback 5.62
# by a wide margin; 8.0 separates the two populations conservatively
print("good" if hw and (d.get("value") or 0) >= 8.0 else "bad")
EOF
)
    say "ladder attempt $i: $ok ($(echo "$line" | head -c 160))"
    if [ "$ok" = "good" ]; then
        # append banked_at via json load/dump like the other harvest
        # steps — sed on the raw line silently banked corrupted (or
        # timestamp-less) JSON whenever the line wasn't }-terminated
        ts=$(date -u +%Y-%m-%dT%H:%M:%SZ) python - "$line" <<'EOF' \
            > .bench_r5c.banked.tmp && mv .bench_r5c.banked.tmp BENCH_LOCAL.json
import json, os, sys
d = json.loads(sys.argv[1])
d["banked_at"] = os.environ["ts"]
json.dump(d, sys.stdout)
EOF
        ladder_ok=1
        break
    fi
    sleep 120
done
[ -n "$ladder_ok" ] && say "post-fix ladder banked to BENCH_LOCAL.json" \
    || say "ladder never cleared the pallas bar; BENCH_LOCAL left as-is"

# ---- 2. overlap A/B at the headline, both banked fresh -------------
run_single roi_ab_overlap_off_1344 EKSML_BWD_OVERLAP=0 -- \
    --steps 10 --image-size 1344 --batch-size 4 \
    --roi-backend pallas --roi-bwd pallas
run_single roi_ab_overlap_on_1344 EKSML_BWD_OVERLAP=1 -- \
    --steps 10 --image-size 1344 --batch-size 4 \
    --roi-backend pallas --roi-bwd pallas
python - >> "$LOG" 2>&1 <<'EOF'
import json
rows = []
for tag in ("roi_ab_overlap_off_1344", "roi_ab_overlap_on_1344"):
    try:
        d = json.load(open(f"artifacts/{tag}.json"))
    except Exception:
        continue
    rows.append({"run": tag, **{k: d.get(k) for k in (
        "value", "step_time_ms", "mfu", "device_kind", "error")}})
json.dump({"runs": rows},
          open("artifacts/roi_ab_overlap_r5b.json", "w"), indent=1)
print("merged overlap A/B:", rows)
EOF
say "overlap A/B merged"

# ---- 3. long hardware convergence, cache warm + patient probe ------
wait_slot
wait_port
say "long TPU convergence: 2500 steps @512/b4 (probe timeout 600)"
conv_dir=$(mktemp -d /tmp/shapes_coco_r5c.XXXXXX)
python - "$conv_dir" >> "$LOG" 2>&1 <<'EOF'
import sys
from tools.make_shapes_coco import make_split
base = sys.argv[1]
make_split(base, "train2017", 200, 512, 0, 1000)
make_split(base, "val2017", 30, 512, 1, 100000)
print("r5c dataset at", base)
EOF
conv_metrics="$conv_dir/run/metrics.jsonl"
EKSML_PROBE_TIMEOUT=600 \
python tools/convergence_run.py --steps 2500 --size 512 --batch-size 4 \
    --data "$conv_dir" \
    --out artifacts/convergence_r5_tpu_long.json \
    --config RPN.TRAIN_PRE_NMS_TOPK=512 RPN.TRAIN_POST_NMS_TOPK=128 \
    RPN.TEST_PRE_NMS_TOPK=512 RPN.TEST_POST_NMS_TOPK=128 \
    FRCNN.BATCH_PER_IM=128 TRAIN.GRADIENT_CLIP=0.36 BACKBONE.NORM=GN \
    >> "$LOG" 2>&1 &
conv_pid=$!
for _ in $(seq 45); do
    sleep 60
    kill -0 "$conv_pid" 2>/dev/null || break
    if [ -s "$conv_metrics" ]; then
        say "convergence stepping; watchdog standing down"
        break
    fi
done
if kill -0 "$conv_pid" 2>/dev/null && [ ! -s "$conv_metrics" ]; then
    say "convergence wrote ZERO steps in 45 min — killing hung client"
    kill "$conv_pid" 2>/dev/null
fi
wait "$conv_pid" 2>/dev/null
if reason=$(python -c '
import json, sys
try:
    d = json.load(open("artifacts/convergence_r5_tpu_long.json"))
except Exception:
    print("no artifact"); sys.exit(1)
if d.get("device", "").lower() in ("", "cpu", "host"):
    print("ran on CPU fallback"); sys.exit(1)
old = json.load(open("artifacts/convergence_r3.json"))
if d.get("bbox_AP50", 0) < old.get("bbox_AP50", 0):
    print("AP50 %.3f below r3 bar %.3f" % (
        d.get("bbox_AP50", 0), old.get("bbox_AP50", 0)))
    sys.exit(1)
'); then
    cp artifacts/convergence_r5_tpu_long.json artifacts/convergence_r5.json
    say "long convergence PROMOTED to convergence_r5.json"
else
    say "long convergence not promoted: $reason"
fi
say "r5c harvest complete"
