#!/bin/bash
# Round-5d harvest: after r5c banks the headline evidence, spend the
# remaining window on ATTRIBUTION — which ops actually moved.
#   1. op_microbench on the TPU (old-vs-new NMS + matching at the
#      production 1344/b4 shapes) -> artifacts/op_microbench_tpu.json
#   2. fresh profiled headline bench + trace summary (freshness-guarded
#      like the patched r5b: only summarize a trace THIS run produced)
#   3. batch-8 headline probe (MFU headroom)
# Same tunnel discipline: one client at a time, port-wait, never kill.
set -u
cd "$(dirname "$0")/.."
LOG=tpu_harvest_r5d.log

say() { echo "[r5d] $(date -u +%H:%M:%S) $*" >> "$LOG"; }

wait_slot() {
    while pgrep -af \
        "python bench.py|tools/convergence_run.py|tools/op_microbench.py" \
        2>/dev/null | grep -v "platform cpu" | grep -q .; do
        sleep 60
    done
}

wait_port() {
    local n=0
    while ! python - <<'EOF'
import socket, sys
try:
    socket.create_connection(("127.0.0.1", 8103), timeout=0.75).close()
except OSError:
    sys.exit(1)
EOF
    do
        n=$((n + 1))
        [ $((n % 20)) -eq 1 ] && say "tunnel port closed (x$n); waiting"
        sleep 30
    done
}

run_single() {  # run_single <tag> <extra env...> -- <bench args...>
    local tag=$1; shift
    local envs=()
    while [ "$1" != "--" ]; do envs+=("$1"); shift; done
    shift
    wait_slot
    wait_port
    say "run $tag: ${envs[*]:-} bench.py --single $*"
    env "${envs[@]}" python bench.py --single "$@" \
        --init-retries 3 --init-timeout 300 \
        2>>"$LOG" | tail -1 > "artifacts/$tag.json.tmp"
    if python -c "import json,sys; json.load(open(sys.argv[1]))" \
        "artifacts/$tag.json.tmp" 2>/dev/null; then
        mv "artifacts/$tag.json.tmp" "artifacts/$tag.json"
        say "done $tag: $(head -c 200 "artifacts/$tag.json")"
    else
        rm -f "artifacts/$tag.json.tmp"
        say "FAILED $tag: bench produced no JSON (see $LOG)"
    fi
}

say "waiting for r5c to finish"
while ! grep -q "r5c harvest complete" tpu_harvest_r5c.log 2>/dev/null; do
    sleep 120
done
say "r5c done; starting attribution runs"

# ---- 1. op microbench at production shapes -------------------------
wait_slot
wait_port
say "op_microbench (TPU, 1344 shapes)"
python tools/op_microbench.py --iters 20 --image-size 1344 \
    --batch 4 --pre-nms 2000 \
    --out artifacts/op_microbench_tpu.json >> "$LOG" 2>&1 \
    && say "op_microbench banked: $(head -c 300 artifacts/op_microbench_tpu.json)" \
    || say "op_microbench FAILED (see $LOG)"

# ---- 2. fresh profile, freshness-guarded ---------------------------
rm -f artifacts/bench_profiled_r5b.json
run_single bench_profiled_r5b -- --steps 10 --image-size 1344 \
    --batch-size 4 --profile 8
if python - <<'EOF'
import json, sys
try:
    d = json.load(open("artifacts/bench_profiled_r5b.json"))
except Exception:
    sys.exit(1)
sys.exit(0 if (d.get("value") or 0) > 0 else 1)
EOF
then
    if python tools/trace_summary.py profile \
        --out artifacts/profile_summary_r5b.json >> "$LOG" 2>&1; then
        say "fresh profile summary banked"
    fi
else
    say "profiled bench failed; NOT summarizing the stale trace"
fi

# ---- 3. batch-8 headline probe -------------------------------------
run_single bench_1344_b8 -- --steps 10 --image-size 1344 --batch-size 8
say "r5d harvest complete"
