#!/bin/bash
# Round-6 harvest: the profile-attributed step-time pipeline on real
# hardware.  Converts the first healthy tunnel window into the
# evidence chain ISSUE 3 / VERDICT r5 next #1/#3/#5/#7 ask for:
#   1. the full cheap-first ladder (now ends at the 1344/b8
#      remat+bf16-param rung -> the >=13 img/s/chip candidate headline)
#   2. per-change A/B at the b4 flagship: prefetch 0 vs 1
#   3. profiled headline run -> profile/attribution.json (HLO
#      component map) -> trace_summary --attribution (component_pct
#      with "other" <=30, replacing the unreadable r5 profile)
#   4. op_microbench --bank (old-vs-new per-op ladder, the part-2
#      attribution mystery's second artifact)
# Same tunnel discipline as r5*: one client at a time, port-wait,
# never kill a running client.
set -u
cd "$(dirname "$0")/.."
LOG=tpu_harvest_r6.log

say() { echo "[r6] $(date -u +%H:%M:%S) $*" >> "$LOG"; }

wait_slot() {
    while pgrep -af \
        "python bench.py|tools/convergence_run.py|tools/op_microbench.py" \
        2>/dev/null | grep -v "platform cpu" | grep -q .; do
        sleep 60
    done
}

wait_port() {
    local n=0
    while ! python - <<'EOF'
import socket, sys
try:
    socket.create_connection(("127.0.0.1", 8103), timeout=0.75).close()
except OSError:
    sys.exit(1)
EOF
    do
        n=$((n + 1))
        [ $((n % 20)) -eq 1 ] && say "tunnel port closed (x$n); waiting"
        sleep 30
    done
}

run_single() {  # run_single <tag> -- <bench args...>
    local tag=$1; shift; shift  # consume tag and "--"
    wait_slot
    wait_port
    say "run $tag: bench.py --single $*"
    python bench.py --single "$@" \
        --init-retries 3 --init-timeout 300 \
        2>>"$LOG" | tail -1 > "artifacts/$tag.json.tmp"
    if python -c "import json,sys; json.load(open(sys.argv[1]))" \
        "artifacts/$tag.json.tmp" 2>/dev/null; then
        mv "artifacts/$tag.json.tmp" "artifacts/$tag.json"
        say "done $tag: $(head -c 200 "artifacts/$tag.json")"
    else
        rm -f "artifacts/$tag.json.tmp"
        say "FAILED $tag: bench produced no JSON (see $LOG)"
    fi
}

say "r6 harvest starting"

# ---- 1. the ladder, through the b8 memory-plan rung ----------------
wait_slot
wait_port
say "ladder (banks every rung incl. 1344_b8_remat)"
python bench.py --steps 20 --init-retries 3 --init-timeout 300 \
    2>>"$LOG" | tail -1 > artifacts/bench_ladder_r6.json.tmp
mv artifacts/bench_ladder_r6.json.tmp artifacts/bench_ladder_r6.json \
    2>/dev/null && say "ladder: $(head -c 200 artifacts/bench_ladder_r6.json)"

# ---- 2. prefetch A/B at the b4 flagship ----------------------------
run_single bench_1344_b4_prefetch0 -- --steps 15 --image-size 1344 \
    --batch-size 4 --prefetch 0
run_single bench_1344_b4_prefetch1 -- --steps 15 --image-size 1344 \
    --batch-size 4 --prefetch 1

# ---- 3. profiled headline + component-attributed summary -----------
rm -f artifacts/bench_profiled_r6.json
run_single bench_profiled_r6 -- --steps 10 --image-size 1344 \
    --batch-size 4 --profile 8
if python - <<'EOF'
import json, sys
try:
    d = json.load(open("artifacts/bench_profiled_r6.json"))
except Exception:
    sys.exit(1)
sys.exit(0 if (d.get("value") or 0) > 0 else 1)
EOF
then
    # the attribution artifact was written by THIS profiled run
    # (bench --profile banks profile/attribution.json alongside the
    # trace), so summarize with component resolution
    if python tools/trace_summary.py profile \
        --attribution profile/attribution.json \
        --out artifacts/profile_summary_r6.json >> "$LOG" 2>&1; then
        say "component-attributed profile summary banked: $(python -c "
import json
d = json.load(open('artifacts/profile_summary_r6.json'))
print('other', d.get('component_other_pct'))" 2>/dev/null)"
    fi
else
    say "profiled bench failed; NOT summarizing the stale trace"
fi

# ---- 4. op microbench, banked-artifact mode ------------------------
wait_slot
wait_port
say "op_microbench --bank (TPU, 1344 shapes)"
python tools/op_microbench.py --iters 20 --image-size 1344 \
    --batch 4 --pre-nms 2000 --bank >> "$LOG" 2>&1 \
    && say "op_microbench banked: $(head -c 300 artifacts/op_microbench_tpu.json 2>/dev/null)" \
    || say "op_microbench FAILED (see $LOG)"

# ---- 5. fresh calibration point for the hermetic perf gate ---------
# Every rung artifact the ladder just banked carries BOTH measured and
# predicted step time (bench.py emits them side by side since ISSUE
# 7), so the roofline model's honesty check gains a fresh hardware
# point the moment the window closes.  Pure CPU JSON math — no
# tunnel, runs even if every hardware block above failed (it then
# re-reports the r5-based fit unchanged).
say "perf-gate calibration (predicted vs this window's measurements)"
JAX_PLATFORMS=cpu python tools/perf_gate.py --calibrate-only \
    --out artifacts/perf_calibration_r6.json >> "$LOG" 2>&1 \
    && say "calibration banked: $(python -c "
import json
d = json.load(open('artifacts/perf_calibration_r6.json'))
c = d.get('calibration', {})
print('points', c.get('n_points'), 'scale', c.get('scale'),
      'model_error_pct', c.get('model_error_pct'))" 2>/dev/null)" \
    || say "calibration FAILED (see $LOG)"

say "r6 harvest complete"
