"""Summarize a jax.profiler trace into a per-op-family time breakdown.

Answers VERDICT r2 next #5: where does the train step actually go —
backbone/FPN convs, ROIAlign forward, ROIAlign backward, NMS, resnet
head — so the Pallas-backward go/no-go is decided on data, not vibes.

Reads the TensorBoard-format ``*.trace.json.gz`` the profiler writes
under ``<dir>/plugins/profile/<run>/`` and aggregates device-lane event
durations by family (regex over XLA fusion/custom-call names).

Component attribution (VERDICT r5 weak #3: fusion names like "5"/"23"
put 86.78% of device time in "other"): pass ``--attribution`` (the
``profile/attribution.json`` artifact ``bench.py --profile`` banks) or
``--hlo`` (a raw ``Compiled.as_text()`` dump) and every event name is
first resolved through the compiled module's instruction→component map
(eksml_tpu/profiling), yielding a ``component_pct`` table — rpn-nms /
roi-bwd / fpn-conv-bwd / optimizer / allreduce … — alongside the
legacy name-regex families.

Usage::

    python tools/trace_summary.py profile --out artifacts/profile_summary_r3.json
    python tools/trace_summary.py profile --attribution profile/attribution.json
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import re
import sys

# op-name regex → family, first match wins.  XLA fusion names carry the
# dominant op (e.g. "fusion.123" with metadata, or "%convolution.45");
# pallas kernels keep their kernel name.
FAMILIES = (
    ("roi_align_bwd", r"roi.?align.*(bwd|backward|grad|transpose)|"
                      r"(bwd|backward|grad).*roi.?align"),
    ("roi_align_fwd", r"roi.?align"),
    ("nms", r"non.?max|nms"),
    ("conv", r"conv"),
    ("matmul", r"dot|gemm|matmul|einsum"),
    ("allreduce", r"all.?reduce|psum|reduce.?scatter|all.?gather|"
                  r"collective"),
    ("copy", r"copy|transpose|reshape|bitcast"),
    ("reduce", r"reduce|cumsum|sort|top.?k"),
    ("scatter_gather", r"scatter|gather|dynamic.?slice|dynamic.?update"),
)


def _load_trace_events(trace_dir: str):
    pats = [os.path.join(trace_dir, "**", "*.trace.json.gz"),
            os.path.join(trace_dir, "**", "*.trace.json")]
    paths = [p for pat in pats for p in glob.glob(pat, recursive=True)]
    if not paths:
        raise FileNotFoundError(
            f"no *.trace.json(.gz) under {trace_dir!r} — run with "
            "--profile first")
    path = max(paths, key=os.path.getmtime)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f).get("traceEvents", []), path


def load_component_map(attribution_path: str | None = None,
                       hlo_path: str | None = None) -> dict:
    """Instruction-name → component lookup with trace-name aliases.

    Trace event names drift from HLO instruction names (observed r5:
    events named "5" for "fusion.5", with or without a leading '%') —
    so each map entry also registers its bare numeric suffix as an
    alias when that suffix is unambiguous across instructions.
    """
    if attribution_path:
        with open(attribution_path) as f:
            payload = json.load(f)
        base = payload.get("map", payload)
    elif hlo_path:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from eksml_tpu.profiling import attribution_map

        with open(hlo_path) as f:
            base = attribution_map(f.read())
    else:
        return {}
    out = dict(base)
    suffix: dict = {}
    for name, comp in base.items():
        m = re.match(r"^[\w\-]+\.(\d+)$", name)
        if m:
            suffix.setdefault(m.group(1), set()).add(comp)
    for sfx, comps in suffix.items():
        if len(comps) == 1 and sfx not in out:
            out[sfx] = next(iter(comps))
    return out


def _resolve_component(name: str, cmap: dict) -> str | None:
    n = name.strip().lstrip("%")
    if n in cmap:
        return cmap[n]
    # events sometimes carry a scope prefix ("cluster/fusion.5")
    tail = n.rsplit("/", 1)[-1]
    return cmap.get(tail)


def summarize(trace_dir: str, top_n: int = 15,
              component_map: dict | None = None) -> dict:
    events, path = _load_trace_events(trace_dir)
    # device lanes: TPU/accelerator op events carry "dur" (µs) and live
    # on pids whose process_name mentions the device; host python lanes
    # are excluded so the breakdown is device time, not dispatch time
    pid_names = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pid_names[ev["pid"]] = ev.get("args", {}).get("name", "")
    device_pids = {pid for pid, name in pid_names.items()
                   if re.search(r"tpu|device|/device|xla", name, re.I)
                   and not re.search(r"host|python", name, re.I)}

    fam_us: dict = {}
    comp_us: dict = {}
    op_us: dict = {}
    op_comp: dict = {}
    total = 0.0
    cmap = component_map or {}
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        if device_pids and ev.get("pid") not in device_pids:
            continue
        name = ev.get("name", "")
        dur = float(ev["dur"])
        total += dur
        op_us[name] = op_us.get(name, 0.0) + dur
        if cmap:
            comp = _resolve_component(name, cmap) or "other"
            comp_us[comp] = comp_us.get(comp, 0.0) + dur
            op_comp[name] = comp
        for fam, pat in FAMILIES:
            if re.search(pat, name, re.I):
                fam_us[fam] = fam_us.get(fam, 0.0) + dur
                break
        else:
            fam_us["other"] = fam_us.get("other", 0.0) + dur

    if total == 0:
        raise ValueError(
            f"no device-lane events found in {path!r} (pids matched: "
            f"{sorted(device_pids)}) — truncated capture or unexpected "
            "lane naming")
    fam_pct = {k: round(100 * v / total, 2)
               for k, v in sorted(fam_us.items(), key=lambda kv: -kv[1])}
    top_ops = [{"name": k, "us": round(v, 1),
                "pct": round(100 * v / total, 2),
                **({"component": op_comp[k]} if k in op_comp else {})}
               for k, v in sorted(op_us.items(),
                                  key=lambda kv: -kv[1])[:top_n]]
    out = {"trace": path, "total_device_us": round(total, 1),
           "family_pct": fam_pct, "top_ops": top_ops}
    if cmap:
        out["component_pct"] = {
            k: round(100 * v / total, 2)
            for k, v in sorted(comp_us.items(), key=lambda kv: -kv[1])}
        out["component_other_pct"] = out["component_pct"].get("other",
                                                              0.0)
    return out


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("trace_dir")
    p.add_argument("--out", default=None)
    p.add_argument("--top", type=int, default=15)
    p.add_argument("--attribution", default=None,
                   help="profile/attribution.json from bench.py "
                        "--profile: resolve event names to model "
                        "components (eksml_tpu/profiling)")
    p.add_argument("--hlo", default=None,
                   help="raw Compiled.as_text() dump to build the "
                        "component map from (alternative to "
                        "--attribution)")
    args = p.parse_args(argv)
    try:
        cmap = load_component_map(args.attribution, args.hlo)
        summary = summarize(args.trace_dir, args.top,
                            component_map=cmap)
    except (FileNotFoundError, ValueError, OSError) as e:
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 1
    out = json.dumps(summary, indent=1)
    print(out)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
