"""Summarize a jax.profiler trace into a per-op-family time breakdown.

Answers VERDICT r2 next #5: where does the train step actually go —
backbone/FPN convs, ROIAlign forward, ROIAlign backward, NMS, resnet
head — so the Pallas-backward go/no-go is decided on data, not vibes.

Reads the TensorBoard-format ``*.trace.json.gz`` the profiler writes
under ``<dir>/plugins/profile/<run>/`` and aggregates device-lane event
durations by family (regex over XLA fusion/custom-call names).

Usage::

    python tools/trace_summary.py profile --out artifacts/profile_summary_r3.json
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import re
import sys

# op-name regex → family, first match wins.  XLA fusion names carry the
# dominant op (e.g. "fusion.123" with metadata, or "%convolution.45");
# pallas kernels keep their kernel name.
FAMILIES = (
    ("roi_align_bwd", r"roi.?align.*(bwd|backward|grad|transpose)|"
                      r"(bwd|backward|grad).*roi.?align"),
    ("roi_align_fwd", r"roi.?align"),
    ("nms", r"non.?max|nms"),
    ("conv", r"conv"),
    ("matmul", r"dot|gemm|matmul|einsum"),
    ("allreduce", r"all.?reduce|psum|reduce.?scatter|all.?gather|"
                  r"collective"),
    ("copy", r"copy|transpose|reshape|bitcast"),
    ("reduce", r"reduce|cumsum|sort|top.?k"),
    ("scatter_gather", r"scatter|gather|dynamic.?slice|dynamic.?update"),
)


def _load_trace_events(trace_dir: str):
    pats = [os.path.join(trace_dir, "**", "*.trace.json.gz"),
            os.path.join(trace_dir, "**", "*.trace.json")]
    paths = [p for pat in pats for p in glob.glob(pat, recursive=True)]
    if not paths:
        raise FileNotFoundError(
            f"no *.trace.json(.gz) under {trace_dir!r} — run with "
            "--profile first")
    path = max(paths, key=os.path.getmtime)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f).get("traceEvents", []), path


def summarize(trace_dir: str, top_n: int = 15) -> dict:
    events, path = _load_trace_events(trace_dir)
    # device lanes: TPU/accelerator op events carry "dur" (µs) and live
    # on pids whose process_name mentions the device; host python lanes
    # are excluded so the breakdown is device time, not dispatch time
    pid_names = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pid_names[ev["pid"]] = ev.get("args", {}).get("name", "")
    device_pids = {pid for pid, name in pid_names.items()
                   if re.search(r"tpu|device|/device|xla", name, re.I)
                   and not re.search(r"host|python", name, re.I)}

    fam_us: dict = {}
    op_us: dict = {}
    total = 0.0
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        if device_pids and ev.get("pid") not in device_pids:
            continue
        name = ev.get("name", "")
        dur = float(ev["dur"])
        total += dur
        op_us[name] = op_us.get(name, 0.0) + dur
        for fam, pat in FAMILIES:
            if re.search(pat, name, re.I):
                fam_us[fam] = fam_us.get(fam, 0.0) + dur
                break
        else:
            fam_us["other"] = fam_us.get("other", 0.0) + dur

    if total == 0:
        raise ValueError(
            f"no device-lane events found in {path!r} (pids matched: "
            f"{sorted(device_pids)}) — truncated capture or unexpected "
            "lane naming")
    fam_pct = {k: round(100 * v / total, 2)
               for k, v in sorted(fam_us.items(), key=lambda kv: -kv[1])}
    top_ops = [{"name": k, "us": round(v, 1),
                "pct": round(100 * v / total, 2)}
               for k, v in sorted(op_us.items(),
                                  key=lambda kv: -kv[1])[:top_n]]
    return {"trace": path, "total_device_us": round(total, 1),
            "family_pct": fam_pct, "top_ops": top_ops}


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("trace_dir")
    p.add_argument("--out", default=None)
    p.add_argument("--top", type=int, default=15)
    args = p.parse_args(argv)
    try:
        summary = summarize(args.trace_dir, args.top)
    except (FileNotFoundError, ValueError, OSError) as e:
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 1
    out = json.dumps(summary, indent=1)
    print(out)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            f.write(out + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
