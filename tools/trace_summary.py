"""Summarize a jax.profiler trace into a per-op-family time breakdown.

Answers VERDICT r2 next #5: where does the train step actually go —
backbone/FPN convs, ROIAlign forward, ROIAlign backward, NMS, resnet
head — so the Pallas-backward go/no-go is decided on data, not vibes.

Reads the TensorBoard-format ``*.trace.json.gz`` the profiler writes
under ``<dir>/plugins/profile/<run>/`` and aggregates device-lane event
durations by family (regex over XLA fusion/custom-call names).

Component attribution (VERDICT r5 weak #3: fusion names like "5"/"23"
put 86.78% of device time in "other"): pass ``--attribution`` (the
``profile/attribution.json`` artifact ``bench.py --profile`` banks) or
``--hlo`` (a raw ``Compiled.as_text()`` dump) and every event name is
first resolved through the compiled module's instruction→component map
(eksml_tpu/profiling), yielding a ``component_pct`` table — rpn-nms /
roi-bwd / fpn-conv-bwd / optimizer / allreduce … — alongside the
legacy name-regex families.

Cross-host span merge (ISSUE 5): with ``--merge`` the positional
argument is a training LOGDIR holding the per-host span traces the
telemetry tracer flushes (``trace-host<i>.json``,
eksml_tpu/telemetry/tracing.py).  Host clocks are re-aligned on step
boundaries (the median per-step offset of each host's ``train_step``
span against host 0 — NTP skew cannot corrupt the timeline), the
events merge into ONE Chrome-trace document (``pid`` = host), and the
summary names the slowest steps with the dominant span on the
slowest host — "step 412 was slow because host 3 sat 1.9 s in
data_wait" instead of a bare ``hosts/lagging`` index.

Usage::

    python tools/trace_summary.py profile --out artifacts/profile_summary_r3.json
    python tools/trace_summary.py profile --attribution profile/attribution.json
    python tools/trace_summary.py <logdir> --merge --out merged_trace.json
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import re
import sys

# op-name regex → family, first match wins.  XLA fusion names carry the
# dominant op (e.g. "fusion.123" with metadata, or "%convolution.45");
# pallas kernels keep their kernel name.
FAMILIES = (
    ("roi_align_bwd", r"roi.?align.*(bwd|backward|grad|transpose)|"
                      r"(bwd|backward|grad).*roi.?align"),
    ("roi_align_fwd", r"roi.?align"),
    ("nms", r"non.?max|nms"),
    ("conv", r"conv"),
    ("matmul", r"dot|gemm|matmul|einsum"),
    ("allreduce", r"all.?reduce|psum|reduce.?scatter|all.?gather|"
                  r"collective"),
    ("copy", r"copy|transpose|reshape|bitcast"),
    ("reduce", r"reduce|cumsum|sort|top.?k"),
    ("scatter_gather", r"scatter|gather|dynamic.?slice|dynamic.?update"),
)


def _load_trace_events(trace_dir: str):
    pats = [os.path.join(trace_dir, "**", "*.trace.json.gz"),
            os.path.join(trace_dir, "**", "*.trace.json")]
    paths = [p for pat in pats for p in glob.glob(pat, recursive=True)]
    if not paths:
        raise FileNotFoundError(
            f"no *.trace.json(.gz) under {trace_dir!r} — run with "
            "--profile first")
    path = max(paths, key=os.path.getmtime)
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f).get("traceEvents", []), path


def load_component_map(attribution_path: str | None = None,
                       hlo_path: str | None = None) -> dict:
    """Instruction-name → component lookup with trace-name aliases.

    Trace event names drift from HLO instruction names (observed r5:
    events named "5" for "fusion.5", with or without a leading '%') —
    so each map entry also registers its bare numeric suffix as an
    alias when that suffix is unambiguous across instructions.
    """
    if attribution_path:
        with open(attribution_path) as f:
            payload = json.load(f)
        base = payload.get("map", payload)
    elif hlo_path:
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from eksml_tpu.profiling import attribution_map

        with open(hlo_path) as f:
            base = attribution_map(f.read())
    else:
        return {}
    out = dict(base)
    suffix: dict = {}
    for name, comp in base.items():
        m = re.match(r"^[\w\-]+\.(\d+)$", name)
        if m:
            suffix.setdefault(m.group(1), set()).add(comp)
    for sfx, comps in suffix.items():
        if len(comps) == 1 and sfx not in out:
            out[sfx] = next(iter(comps))
    return out


def _resolve_component(name: str, cmap: dict) -> str | None:
    n = name.strip().lstrip("%")
    if n in cmap:
        return cmap[n]
    # events sometimes carry a scope prefix ("cluster/fusion.5")
    tail = n.rsplit("/", 1)[-1]
    return cmap.get(tail)


def summarize(trace_dir: str, top_n: int = 15,
              component_map: dict | None = None) -> dict:
    events, path = _load_trace_events(trace_dir)
    # device lanes: TPU/accelerator op events carry "dur" (µs) and live
    # on pids whose process_name mentions the device; host python lanes
    # are excluded so the breakdown is device time, not dispatch time
    pid_names = {}
    for ev in events:
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            pid_names[ev["pid"]] = ev.get("args", {}).get("name", "")
    device_pids = {pid for pid, name in pid_names.items()
                   if re.search(r"tpu|device|/device|xla", name, re.I)
                   and not re.search(r"host|python", name, re.I)}

    fam_us: dict = {}
    comp_us: dict = {}
    op_us: dict = {}
    op_comp: dict = {}
    total = 0.0
    cmap = component_map or {}
    for ev in events:
        if ev.get("ph") != "X" or "dur" not in ev:
            continue
        if device_pids and ev.get("pid") not in device_pids:
            continue
        name = ev.get("name", "")
        dur = float(ev["dur"])
        total += dur
        op_us[name] = op_us.get(name, 0.0) + dur
        if cmap:
            comp = _resolve_component(name, cmap) or "other"
            comp_us[comp] = comp_us.get(comp, 0.0) + dur
            op_comp[name] = comp
        for fam, pat in FAMILIES:
            if re.search(pat, name, re.I):
                fam_us[fam] = fam_us.get(fam, 0.0) + dur
                break
        else:
            fam_us["other"] = fam_us.get("other", 0.0) + dur

    if total == 0:
        raise ValueError(
            f"no device-lane events found in {path!r} (pids matched: "
            f"{sorted(device_pids)}) — truncated capture or unexpected "
            "lane naming")
    fam_pct = {k: round(100 * v / total, 2)
               for k, v in sorted(fam_us.items(), key=lambda kv: -kv[1])}
    top_ops = [{"name": k, "us": round(v, 1),
                "pct": round(100 * v / total, 2),
                **({"component": op_comp[k]} if k in op_comp else {})}
               for k, v in sorted(op_us.items(),
                                  key=lambda kv: -kv[1])[:top_n]]
    out = {"trace": path, "total_device_us": round(total, 1),
           "family_pct": fam_pct, "top_ops": top_ops}
    if cmap:
        out["component_pct"] = {
            k: round(100 * v / total, 2)
            for k, v in sorted(comp_us.items(), key=lambda kv: -kv[1])}
        out["component_other_pct"] = out["component_pct"].get("other",
                                                              0.0)
    return out


# ---------------------------------------------------------------------
# cross-host span-trace merge (trace-host<i>.json from the telemetry
# tracer) — ISSUE 5
# ---------------------------------------------------------------------

STEP_SPAN = "train_step"  # the per-step anchor span the fit loop emits


def load_host_traces(logdir: str) -> tuple:
    """``({host_id: [events]}, {host_id: reason})`` from every
    ``trace-host<i>.json`` under ``logdir``.

    Skip-and-warn, never abort: a host killed mid-flush leaves a
    truncated/torn trace file, and a host that died before its first
    flush leaves none at all — exactly the runs whose cross-host
    timeline matters most.  Unreadable files are skipped with a
    stderr warning; hosts that the run's ``events-host<i>.jsonl``
    files prove existed but that left no trace are reported missing.
    Only a logdir with NO readable trace at all raises."""
    out: dict = {}
    skipped: dict = {}
    for path in sorted(glob.glob(
            os.path.join(logdir, "trace-host*.json"))):
        m = re.search(r"trace-host(\d+)\.json$", path)
        if not m:
            continue
        host = int(m.group(1))
        try:
            with open(path) as f:
                doc = json.load(f)
            events = doc.get("traceEvents", []) \
                if isinstance(doc, dict) else None
        except (json.JSONDecodeError, OSError) as e:
            # torn write from a killed process — keep the other hosts
            skipped[host] = f"unreadable ({type(e).__name__}: {e})"
            continue
        if not isinstance(events, list):
            skipped[host] = "malformed (no traceEvents list)"
            continue
        out[host] = events
    # hosts the run demonstrably had (their event files exist) but
    # whose span trace never landed — name them instead of silently
    # rendering a timeline that pretends they weren't there
    for path in glob.glob(os.path.join(logdir, "events-host*.jsonl")):
        m = re.search(r"events-host(\d+)\.jsonl$", path)
        if m and int(m.group(1)) not in out \
                and int(m.group(1)) not in skipped:
            skipped[int(m.group(1))] = "missing trace-host file"
    for host in sorted(skipped):
        print(f"warning: skipping host {host}: {skipped[host]} — "
              "merging the remaining hosts", file=sys.stderr)
    if not out:
        raise FileNotFoundError(
            f"no readable trace-host<i>.json under {logdir!r} — run "
            "with TELEMETRY.TRACING.ENABLED=True (or trigger a "
            "/debugz/profile capture) first"
            + (f"; skipped: {skipped}" if skipped else ""))
    return out, skipped


def _step_anchors(events) -> dict:
    """{step: earliest ts} of the per-step anchor spans."""
    anchors: dict = {}
    for ev in events:
        if ev.get("ph") != "X" or ev.get("name") != STEP_SPAN:
            continue
        step = (ev.get("args") or {}).get("step")
        if step is None:
            continue
        ts = float(ev["ts"])
        if step not in anchors or ts < anchors[step]:
            anchors[step] = ts
    return anchors


def merge_host_traces(logdir: str, slow_top: int = 5) -> dict:
    """Merge per-host span traces into one step-aligned timeline.

    Alignment: per host, the median over common steps of (host0's
    anchor ts − this host's anchor ts) becomes the host's clock
    offset.  Step boundaries are collective in SPMD training, so the
    median offset IS the clock skew; wall-clock (NTP) disagreement
    drops out entirely.
    """
    traces, skipped = load_host_traces(logdir)
    ref_host = min(traces)
    ref_anchor = _step_anchors(traces[ref_host])

    merged = []
    offsets = {}
    covered: dict = {}    # step -> {host} (hosts with the anchor span)
    step_durs: dict = {}  # step -> {host: Σ step-attributed span µs}
    span_max: dict = {}   # (step, host) -> (name, dur µs) longest one
    for host, events in sorted(traces.items()):
        anchors = _step_anchors(events)
        common = sorted(set(anchors) & set(ref_anchor))
        if host == ref_host or not common:
            offset = 0.0
        else:
            deltas = sorted(ref_anchor[s] - anchors[s] for s in common)
            offset = deltas[len(deltas) // 2]
        offsets[host] = offset
        for ev in events:
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = round(float(ev["ts"]) + offset, 3)
            ev["pid"] = host
            merged.append(ev)
            if ev.get("ph") != "X":
                continue
            step = (ev.get("args") or {}).get("step")
            if step is None:
                continue
            step = int(step)
            dur = float(ev.get("dur", 0.0))
            if ev.get("name") == STEP_SPAN:
                covered.setdefault(step, set()).add(host)
            # per-step host wall = the SUM of the loop's sequential
            # step-attributed spans, not the train_step dispatch
            # alone: on an accelerator the dispatch returns
            # immediately and the blocking lands in data_wait /
            # host_metrics — ranking by dispatch would structurally
            # hide input starvation, the main thing to catch
            cur = step_durs.setdefault(step, {})
            cur[host] = cur.get(host, 0.0) + dur
            best = span_max.get((step, host))
            if best is None or dur > best[1]:
                span_max[(step, host)] = (ev["name"], dur)
    merged.sort(key=lambda e: e.get("ts", 0.0))

    # per-step wall time = the slowest host's total (the synchronous-
    # SPMD bound); only anchor-covered steps count (a lone
    # host_metrics span from a partial capture is not a step)
    steps = []
    for step in sorted(covered):
        by_host = {h: d for h, d in step_durs[step].items()
                   if h in covered[step]}
        if not by_host:
            continue
        slow_host = max(by_host, key=by_host.get)
        steps.append({"step": step,
                      "ms": round(by_host[slow_host] / 1000.0, 3),
                      "host": slow_host,
                      "hosts": len(by_host)})
    slow_steps = []
    if steps:
        mean_ms = sum(s["ms"] for s in steps) / len(steps)
        for s in sorted(steps, key=lambda s: -s["ms"])[:slow_top]:
            entry = dict(s)
            entry["vs_mean"] = round(s["ms"] / mean_ms, 2) \
                if mean_ms > 0 else 0.0
            dom = span_max.get((s["step"], s["host"]))
            if dom is not None:
                entry["dominant_span"] = dom[0]
                entry["dominant_ms"] = round(dom[1] / 1000.0, 3)
            slow_steps.append(entry)

    return {
        "hosts": sorted(traces),
        "skipped_hosts": {str(h): r
                          for h, r in sorted(skipped.items())},
        "host_offsets_us": {str(h): round(o, 1)
                            for h, o in offsets.items()},
        "steps_covered": len(steps),
        "mean_step_ms": (round(sum(s["ms"] for s in steps)
                               / len(steps), 3) if steps else 0.0),
        "slow_steps": slow_steps,
        "traceEvents": merged,
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("trace_dir")
    p.add_argument("--out", default=None)
    p.add_argument("--top", type=int, default=15)
    p.add_argument("--attribution", default=None,
                   help="profile/attribution.json from bench.py "
                        "--profile: resolve event names to model "
                        "components (eksml_tpu/profiling)")
    p.add_argument("--hlo", default=None,
                   help="raw Compiled.as_text() dump to build the "
                        "component map from (alternative to "
                        "--attribution)")
    p.add_argument("--merge", action="store_true",
                   help="treat the positional arg as a training "
                        "logdir and merge its trace-host<i>.json "
                        "span files into one step-aligned cross-host "
                        "timeline (telemetry tracing, ISSUE 5)")
    args = p.parse_args(argv)
    try:
        if args.merge:
            summary = merge_host_traces(args.trace_dir)
        else:
            cmap = load_component_map(args.attribution, args.hlo)
            summary = summarize(args.trace_dir, args.top,
                                component_map=cmap)
    except (FileNotFoundError, ValueError, OSError) as e:
        print(json.dumps({"error": f"{type(e).__name__}: {e}"}))
        return 1
    if args.merge:
        # stdout gets the human-relevant verdict; the (large) merged
        # timeline only lands where --out asks for it
        printed = {k: v for k, v in summary.items()
                   if k != "traceEvents"}
        print(json.dumps(printed, indent=1))
    else:
        print(json.dumps(summary, indent=1))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            f.write(json.dumps(summary, indent=1) + "\n")
        os.replace(tmp, args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
